"""Continuous-batching inference engine.

The single-request generators (models/generate.py, models/decode.py:
``generate_cached``) answer one prompt at a time; a serving workload has
many concurrent users with different prompt lengths, arrival times and
sampling params. This engine closes that gap with the two standard
techniques:

- **Slot-pool KV cache**: one fixed ``init_cache(cfg, num_slots)``
  pool holds every in-flight sequence's K/V rings. A request owns one
  slot row from admission to retirement; rows are reused WITHOUT
  clearing because the ring mask derives visibility purely from
  position arithmetic (models/decode.py: ``_attn_chunk``) — a fresh
  prefill at pos=0 makes every stale key invisible by construction.
  With ``ServingConfig.kv_page_size > 0`` the pool is PAGED
  (vLLM-style, serving/pages.py): device KV lives in fixed-size pages
  mapped through per-slot page tables that ride the jitted steps as
  runtime int32 arrays, admission keys on free pages instead of
  slots, and a radix tree shares cached prompt prefixes copy-on-write
  — same ring semantics, same zero-recompile pins.
- **Iteration-level (Orca-style) scheduling**: each :meth:`step` admits
  queued requests into free slots, advances prefill by a bounded token
  budget (serving/scheduler.py), then decodes ALL active slots as one
  batched length-1 ``forward_chunk``. Sequences retire on EOS or
  max-tokens without stalling the rest of the batch; the freed slot is
  refilled on the next iteration.

Everything device-side is shape-static, so continuous batching costs no
recompilation as requests come and go:

- the decode step is one jitted call over the FULL pool — per-slot
  positions/tokens/active-mask are runtime arrays (inactive rows compute
  garbage that a masked cache-merge discards);
- prefill chunks come from a power-of-two ladder, so at most
  log2(prefill_chunk)+1 prefill shapes ever compile;
- sampling is one jitted batched kernel with per-row temperature/top-k
  ARRAYS (models/generate.py:``sample_token`` bakes them into the trace
  as statics; rows here must differ without recompiling). The greedy and
  default paths are bit-identical to ``sample_token`` — pinned by
  tests/test_serving.py.

Mixed per-slot positions ride a ``jax.vmap`` over ``forward_chunk``
(each row carries its own ``pos`` scalar, exactly the traced-position
path the chunked decoder already supports); ``forward_chunk``'s
concrete-position validity guards are enforced host-side at submit
instead. Per-request determinism: the key for the t-th generated token
is ``fold_in(PRNGKey(seed), t)``, a pure function of the request — not
of slot assignment, batch composition, or admission order.

Family limits (models/decode.py module docstring): control/ndiff roll
the ring past block_size up to ``ServingConfig.max_seq_len``; the diff
family's learned absolute position table cannot roll, so its requests
are capped at ``prompt + max_new_tokens <= block_size``.
"""

from __future__ import annotations

import math
import sys
import time
from functools import lru_cache
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from differential_transformer_replication_tpu.config import (
    ModelConfig,
    ServingConfig,
)
from differential_transformer_replication_tpu.models.decode import (
    KV_CACHE_BATCH_AXIS,
    apply_logit_pipeline,
    copy_cache_pages,
    forward_chunk,
    forward_decode_pool,
    forward_decode_pool_paged,
    forward_decode_spec,
    forward_decode_spec_paged,
    gather_slot_cache,
    init_cache,
    init_cache_paged,
    kv_store_dtype,
    merge_cache_update,
    quality_vector,
    scatter_slot_cache,
)
from differential_transformer_replication_tpu.obs.quality import (
    ENTROPY_BINS,
    MARGIN_BINS,
    QualityMonitor,
    build_quality_row,
    load_fingerprint,
)
from differential_transformer_replication_tpu.obs.registry import (
    Registry,
    StatsMap,
)
from differential_transformer_replication_tpu.obs.spans import NOOP_TRACER
from differential_transformer_replication_tpu.obs.trace import (
    TraceContext,
    child_span_args,
    instant_args,
)
from differential_transformer_replication_tpu.serving.constrain import (
    ConstraintCache,
    ConstraintCompileError,
    spec_key,
)
from differential_transformer_replication_tpu.serving.host_tier import (
    TierEntry,
)
from differential_transformer_replication_tpu.serving.migrate import (
    MigrateExportError,
    decode_slot_state,
    encode_slot_state,
    params_from_dict,
    params_to_dict,
)
from differential_transformer_replication_tpu.serving.pages import (
    PagePool,
    PagePoolExhaustedError,
    page_bytes,
)
from differential_transformer_replication_tpu.serving.request import (
    Request,
    RequestOutput,
    SamplingParams,
)
from differential_transformer_replication_tpu.serving.scheduler import (
    ACTIVE,
    FREE,
    Scheduler,
    Slot,
)
from differential_transformer_replication_tpu.utils import faults


# engine.stats keys -> (Prometheus counter name, help). The mapping keys
# are the /health JSON contract (tests/test_serving*.py read them); the
# counter names are the /metrics contract. StatsMap keeps both views over
# ONE set of values so they cannot drift.
_STAT_SPEC = {
    "iterations": (
        "serving_engine_iterations_total",
        "Engine step() iterations executed.",
    ),
    "prefill_tokens": (
        "serving_prefill_tokens_total",
        "Prompt tokens prefilled into KV slots.",
    ),
    "decode_tokens": (
        "serving_decode_tokens_total",
        "Tokens generated by batched decode steps.",
    ),
    "completed": (
        "serving_requests_completed_total",
        "Requests finished normally (eos or length).",
    ),
    "cancelled": (
        "serving_requests_cancelled_total",
        "Requests abandoned by their caller (timeout/cancel).",
    ),
    "rejected": (
        "serving_requests_rejected_total",
        "Submissions rejected at admission (queue full / invalid).",
    ),
    "deadline_expired": (
        "serving_requests_deadline_expired_total",
        "Requests shed or retired past their server-side deadline.",
    ),
    "engine_restarts": (
        "serving_engine_restarts_total",
        "Slot-pool rebuilds after a crashed engine step.",
    ),
    "page_shed": (
        "serving_requests_page_shed_total",
        "Requests shed at admission because the KV page pool could "
        "not hold them (typed PagePoolExhaustedError).",
    ),
    # speculative decoding (serving/spec.py): drafted tokens offered
    # to the fused verify step and how many it accepted — the
    # aggregate acceptance-rate series (per-request counts ride
    # RequestOutput.spec_{proposed,accepted})
    "spec_proposed": (
        "serving_spec_proposed_tokens_total",
        "Draft tokens proposed to the speculative verify step.",
    ),
    "spec_accepted": (
        "serving_spec_accepted_tokens_total",
        "Draft tokens the target model accepted.",
    ),
    "spec_drafter_crashes": (
        "serving_spec_drafter_crashes_total",
        "Drafter pools rebuilt after the finite-logits guard tripped "
        "(engine fell back to non-spec decode, never garbage tokens).",
    ),
    # host-tier / preemption (serving/host_tier.py): the graceful-
    # degradation counters — demote/promote traffic, mid-decode
    # preemptions and bit-exact resumes, and the typed fallbacks where
    # a tier transfer degraded to recompute instead of wedging
    "preemptions": (
        "serving_preemptions_total",
        "Mid-decode preemptions: a lower-priority request's KV pages "
        "stashed to the host tier to unblock a higher class.",
    ),
    "resumes": (
        "serving_preempt_resumes_total",
        "Preempted requests swapped back in bit-exact from their "
        "host-tier stash.",
    ),
    "tier_demotions": (
        "serving_host_tier_demotions_total",
        "Evicted radix pages demoted into the host-RAM tier.",
    ),
    "tier_promotions": (
        "serving_host_tier_promotions_total",
        "Host-tier pages promoted back to device at admission "
        "(a copy, never a recompute).",
    ),
    "tier_fallbacks": (
        "serving_host_tier_fallbacks_total",
        "Tier transfers that degraded to recompute or full restart "
        "(failed/corrupt demote, promote, or swap-in) — typed, "
        "counted, never a wedge.",
    ),
    # live migration (serving/migrate.py): slot states exported to /
    # imported from peer replicas, the wire page traffic (shipped vs
    # radix-deduped), and the typed failures that fell back to
    # resume-by-replay instead of wedging or attending garbage KV
    "migrate_exports": (
        "serving_migrate_exports_total",
        "Slot decode states exported to a peer replica (drain path).",
    ),
    "migrate_imports": (
        "serving_migrate_imports_total",
        "Migrated slot states imported and re-admitted bit-exact.",
    ),
    "migrate_pages_shipped": (
        "serving_migrate_pages_shipped_total",
        "KV pages shipped over the wire by slot-state exports.",
    ),
    "migrate_pages_deduped": (
        "serving_migrate_pages_deduped_total",
        "KV pages NOT shipped because the destination's radix tree "
        "already held the prompt-prefix node (copied device-locally).",
    ),
    "migrate_bytes": (
        "serving_migrate_bytes_total",
        "Wire bytes of exported slot states (post-dedup).",
    ),
    "migrate_failed": (
        "serving_migrate_failed_total",
        "Migration imports that failed after admission (bad checksum, "
        "torn payload, injection failure) — typed, counted, degraded "
        "to a bit-exact recompute, never garbage KV.",
    ),
}


class EngineCrashError(RuntimeError):
    """The engine failed mid-flight (device error, corrupt slot pool,
    non-finite logits). Typed and RETRIABLE: the supervised runner
    (serving/server.py) fails in-flight requests with this error,
    rebuilds the slot pool from params, and serves on — a client that
    retries (HTTP 503 + Retry-After) lands on the restarted engine."""

    retriable = True


@lru_cache(maxsize=None)
def _build_step_fns(cfg: ModelConfig, rope_len: int,
                    page_size: int = 0, num_pages: int = 0,
                    lp_k: int = 5, quality: bool = False):
    """Jitted (prefill, decode, sample, page_copy, page_extract,
    page_inject) closures for (cfg, rope_len[, page geometry], logprob
    echo width). The last three are the paged path's page plumbing
    (COW forks + the host tier's demote/promote transfers) and None on
    the contiguous path.

    Cached at module level so engines with the same model/config share
    compile caches (and tests can count compiles across engine
    rebuilds); every argument below is a runtime array, so each closure
    compiles once per distinct input SHAPE only. With ``page_size > 0``
    the prefill/decode closures run the PAGED cache layout
    (models/decode.py): per-slot page tables and write pages ride in as
    runtime int32 arrays, so page allocation/free/share/fork between
    calls compiles nothing new — the same zero-recompile pin as the
    contiguous path. ``page_copy`` is the COW-fork device copy (None on
    the contiguous path).

    ``quality`` (a static, like lp_k) appends the in-jit quality
    telemetry tail (models/decode.py:``quality_vector``) to the
    sampler's packed output and widens its int operand by one prev-
    token column; False compiles the EXACT pre-telemetry closure, so
    telemetry-off output is bit-identical by construction.
    """
    # cache leaves depend on the KV dtype (int8 adds the scale planes);
    # slicing/scatter/vmap specs derive from the shared axis table so
    # every leaf is handled uniformly (models/decode.py)
    cache_keys = (
        ("k", "v", "k_scale", "v_scale")
        if kv_store_dtype(cfg) == "int8" else ("k", "v")
    )
    row_axes = [
        {key: KV_CACHE_BATCH_AXIS[key] for key in cache_keys}
    ] * cfg.n_layer

    def _row_expand(c, key):
        # one pool row -> the batch-1 layout forward_chunk expects
        return c[key][:, None] if KV_CACHE_BATCH_AXIS[key] else c[key][None]

    def _row_squeeze(c, key):
        return c[key][:, 0] if KV_CACHE_BATCH_AXIS[key] else c[key][0]

    def _one_row(params, token, pos, cache_row):
        # cache_row: per-layer per-slot cache leaves (batch axis sliced
        # away by the vmap); re-add the batch axis forward_chunk expects.
        cache_b = [
            {key: _row_expand(c, key) for key in c} for c in cache_row
        ]
        logits, new_cache = forward_chunk(
            params, token[None, None], pos, cache_b, cfg, rope_len=rope_len
        )
        new_row = [
            {key: _row_squeeze(c, key) for key in c} for c in new_cache
        ]
        return logits[0, -1].astype(jnp.float32), new_row

    if page_size > 0:

        def _decode_paged(params, tokens, pos, cache, page_tables,
                          write_pages):
            """One batched length-1 step over the whole slot pool
            THROUGH the page tables (models/decode.py
            ``forward_decode_pool_paged``): both attention impls
            dispatch inside; inactive rows' writes are redirected to
            the trash page by ``write_pages`` (the paged replacement
            for the contiguous path's masked merge)."""
            logits, new_cache = forward_decode_pool_paged(
                params, tokens, pos, cache, page_tables, write_pages,
                cfg, rope_len=rope_len,
            )
            return logits.astype(jnp.float32), new_cache

        def _prefill_paged(params, cache, page_row, tokens, pos):
            """One prompt chunk for one slot through its page-table
            row: gather the slot's contiguous ring view, run the
            SAME forward_chunk the contiguous path runs (bit-parity by
            construction), scatter the pages back. ``page_row`` is a
            runtime int32 array — only the chunk length L distinguishes
            compiles. Written positions always live on pages the slot
            privately owns (serving/pages.py reserves them at
            admission); shared prefix pages are scattered back with
            their own unchanged values."""
            row = gather_slot_cache(cache, page_row)
            logits, new_row = forward_chunk(
                params, tokens, pos, row, cfg, rope_len=rope_len
            )
            new_cache = scatter_slot_cache(cache, new_row, page_row)
            return logits[0, -1].astype(jnp.float32), new_cache

        def _page_copy(cache, src, dst):
            return copy_cache_pages(cache, src, dst)

        def _page_extract(cache, src):
            """One physical page's leaves sliced out of the pool (the
            host-tier demotion/stash capture). A scalar ``src`` take
            REMOVES the page axis, so each leaf is exactly one page's
            K/V image. NOT donated — the pool stays live; the engine
            fetches the result to host numpy."""
            return [
                {key: jnp.take(c[key], src,
                               axis=KV_CACHE_BATCH_AXIS[key])
                 for key in c}
                for c in cache
            ]

        def _page_inject(cache, dst, payload):
            """Write one page image into physical page ``dst`` (the
            host-tier promotion/swap-in). ``dst`` is a runtime scalar,
            so page placement never recompiles — the same contract as
            ``_page_copy``."""
            out = []
            for c, p in zip(cache, payload):
                layer = {}
                for key in c:
                    axis = KV_CACHE_BATCH_AXIS[key]
                    idx = (slice(None),) * axis + (dst,)
                    layer[key] = c[key].at[idx].set(p[key])
                out.append(layer)
            return out

    if cfg.decode_attention_impl == "pallas":

        def _decode(params, tokens, pos, active, cache):
            """One batched length-1 step over the WHOLE slot pool via the
            pool-native fused path (models/decode.py
            ``forward_decode_pool``): the Pallas decode-attention kernel
            sees every row in one (B*H,)-grid call per layer instead of
            a vmap over rows. Masked-merge semantics identical to the
            XLA variant below."""
            logits, new_cache = forward_decode_pool(
                params, tokens, pos, cache, cfg, rope_len=rope_len
            )
            return (
                logits.astype(jnp.float32),
                merge_cache_update(active, new_cache, cache),
            )

    else:

        def _decode(params, tokens, pos, active, cache):
            """One batched length-1 step over the WHOLE slot pool.

            tokens/pos/active: (B,) runtime arrays. Inactive rows run the
            same math on garbage inputs (static shapes are the point); the
            masked merge below discards their cache writes so a mid-prefill
            or free slot is never corrupted by the fused step.
            """
            logits, new_cache = jax.vmap(
                _one_row, in_axes=(None, 0, 0, row_axes),
                out_axes=(0, row_axes),
            )(params, tokens, pos, cache)
            return logits, merge_cache_update(active, new_cache, cache)

    def _prefill(params, cache, slot, tokens, pos):
        """One prompt chunk for one slot, in place in the pool.

        tokens: (1, L) with L from the power-of-two ladder; slot/pos are
        runtime scalars (dynamic gather/scatter on the pool's batch
        axis), so only L distinguishes compiles.
        """
        row = [
            {key: (c[key][:, slot][:, None]
                   if KV_CACHE_BATCH_AXIS[key] else c[key][slot][None])
             for key in c}
            for c in cache
        ]
        logits, new_row = forward_chunk(
            params, tokens, pos, row, cfg, rope_len=rope_len
        )
        new_cache = [
            {key: (c[key].at[:, slot].set(nr[key][:, 0])
                   if KV_CACHE_BATCH_AXIS[key]
                   else c[key].at[slot].set(nr[key][0]))
             for key in c}
            for c, nr in zip(cache, new_row)
        ]
        return logits[0, -1].astype(jnp.float32), new_cache

    def _sample(ints, logits, allowed, counts_v):
        """Batched per-request sampling over (B, V) fp32 logits,
        through the structured-decoding logit pipeline
        (models/decode.py:``apply_logit_pipeline``).

        Every per-row scalar rides ONE packed (B, 8) int32 operand
        (one host->device conversion per call): token count | top_k |
        PRNG base (2 cols, bitcast uint32) | temperature | repetition
        | presence | frequency penalties (bitcast f32); with
        ``quality`` on, one extra column carries the previous emitted
        token (-1 = none) for the repetition flag. ``allowed``
        (B, V) bool is the per-row constraint-FSM mask row and
        ``counts_v`` (B, V) int32 the generated-token histogram — both
        runtime arrays (the engine passes cached all-ones/zeros
        constants when no active row needs the pipeline), so mixed
        constrained/unconstrained traffic never recompiles. The t-th
        token's key is fold_in(base, t); temperature/top-k semantics
        match sample_token row-for-row (<=0 temp = greedy, top_k <= 0
        = off, mask-below-kth-PROCESSED-logit otherwise). Rows with
        the pipeline inert are BIT-IDENTICAL to the pre-pipeline
        sampler (the pipeline's ``where`` passes raw logits through).

        Output is ONE packed (B, 3 + 2*lp_k) int32 array: token |
        finite-ok | chosen-token logprob (bitcast f32) | top-lp_k ids
        | top-lp_k logprobs (bitcast f32); with ``quality`` on, three
        more bitcast-f32 columns append the quality tail (entropy |
        margin | repeat — existing offsets unchanged). Logprobs are
        over the
        distribution actually sampled from — processed logits after
        top-k, divided by the greedy-safe temperature. The finiteness
        flag is over the RAW logits (before the intentional -inf
        masking): a corrupt KV slot or numerically diverged model
        yields NaN logits, and serving a garbage argmax over them
        would be a silent wrong answer — the engine turns a non-finite
        ACTIVE row into a typed :class:`EngineCrashError` instead
        (inactive rows compute garbage by design and are ignored
        host-side).
        """
        counts = ints[:, 0]
        top_k = ints[:, 1]
        bases = jax.lax.bitcast_convert_type(ints[:, 2:4], jnp.uint32)
        f = jax.lax.bitcast_convert_type(ints[:, 4:8], jnp.float32)
        temperature = f[:, 0]
        keys = jax.vmap(jax.random.fold_in)(bases, counts)
        proc = apply_logit_pipeline(
            logits, allowed, counts_v, f[:, 1], f[:, 2], f[:, 3]
        )
        V = logits.shape[-1]
        kth = jnp.clip(top_k - 1, 0, V - 1)
        sorted_desc = -jnp.sort(-proc, axis=-1)
        thresh = jnp.take_along_axis(sorted_desc, kth[:, None], axis=-1)
        masked = jnp.where(
            (top_k > 0)[:, None] & (proc < thresh), -jnp.inf, proc
        )
        greedy = jnp.argmax(masked, axis=-1)
        safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
        scaled = masked / safe_t
        drawn = jax.vmap(lambda k, lg: jax.random.categorical(k, lg))(
            keys, scaled
        )
        tokens = jnp.where(temperature <= 0, greedy, drawn).astype(jnp.int32)
        lp = jax.nn.log_softmax(scaled, axis=-1)
        chosen = jnp.take_along_axis(lp, tokens[:, None], axis=-1)
        top_lp, top_ids = jax.lax.top_k(lp, lp_k)
        ok = jnp.isfinite(logits).all(axis=-1)
        cols = [
            tokens[:, None],
            ok.astype(jnp.int32)[:, None],
            jax.lax.bitcast_convert_type(chosen, jnp.int32),
            top_ids.astype(jnp.int32),
            jax.lax.bitcast_convert_type(top_lp, jnp.int32),
        ]
        if quality:
            # the telemetry tail rides the SAME packed transfer: the
            # sampled distribution's entropy, the processed-logit
            # margin, and the repeat-of-previous flag per row. The
            # margin reuses sorted_desc's head — the sort already paid
            # for the top-k threshold — so the tail adds no second
            # full-vocab top_k to the fused sampler
            qv = quality_vector(
                lp, proc, tokens, ints[:, 8],
                top2=sorted_desc[:, :2] if V >= 2 else None,
            )
            cols.append(jax.lax.bitcast_convert_type(qv, jnp.int32))
        return jnp.concatenate(cols, axis=1)

    # Donate the cache pool so XLA updates it in place instead of
    # allocating + copying a second full pool per chunk/step (the engine
    # always rebinds self.cache to the result, so the old buffers are
    # dead). CPU has no donation support and would warn on every call.
    donate = jax.default_backend() != "cpu"
    if page_size > 0:
        return (
            jax.jit(_prefill_paged, donate_argnums=(1,) if donate else ()),
            jax.jit(_decode_paged, donate_argnums=(3,) if donate else ()),
            jax.jit(_sample),
            jax.jit(_page_copy, donate_argnums=(0,) if donate else ()),
            jax.jit(_page_extract),  # cache NOT donated: it stays live
            jax.jit(_page_inject, donate_argnums=(0,) if donate else ()),
        )
    return (
        jax.jit(_prefill, donate_argnums=(1,) if donate else ()),
        jax.jit(_decode, donate_argnums=(4,) if donate else ()),
        jax.jit(_sample),
        None,
        None,
        None,
    )


# fold_in salt distinguishing a draft position's ACCEPT-draw key from
# its token key: the t-th token's decisions stay a pure function of
# (request seed, t) — never of slot, batch composition, or how many
# drafts preceded it
_SPEC_ACCEPT_SALT = np.uint32(0x9E3779B9)


@lru_cache(maxsize=None)
def _build_spec_step_fns(cfg: ModelConfig, rope_len: int, draft_len: int,
                         sampled: bool = False, batched: bool = False,
                         page_size: int = 0, num_pages: int = 0,
                         lp_k: int = 5, quality: bool = False):
    """ONE fused jitted verify step for (cfg, rope_len, k rung): the
    L = k+1-row pool forward (models/decode.py:``forward_decode_spec``
    or its paged twin), the per-row sampling transforms, and the
    accept/reject decision — all under ``lax`` ops with k static.
    Per-slot draft lengths, the reject-storm fault flag, page tables
    and write targets ride as RUNTIME arrays, so mixed spec/non-spec
    traffic and varying per-request draft lengths compile NOTHING
    beyond this one rung (the engine's k ladder is {0 = the plain
    decode step, spec_draft_len = this}).

    Acceptance semantics (Leviathan et al. 2023, one-hot drafter):
    greedy rows (temperature <= 0) accept draft j iff it equals row
    j-1's argmax — which makes spec-on greedy output BIT-IDENTICAL to
    the non-spec path by induction (row 0's math is exactly
    ``_build_step_fns``'s ``_sample``). Sampled rows accept draft j
    with probability p_j(d_j) under the temperature/top-k-processed
    target distribution (a salted fold_in of the token's own key draws
    the uniform) and on rejection sample the residual — the target
    distribution with the rejected token masked out, renormalized —
    with the token's UNsalted key, so a non-spec row (draft_len 0)
    reduces to ``_sample`` exactly, bit for bit.

    Returns ``(tokens_out (B, L), n_emit (B,), ok (B,), new_cache)``:
    the accepted prefix plus one corrected/bonus token per slot, and
    the finite-logits flags over each slot's USED rows (the guard that
    turns a corrupt pool into a typed EngineCrashError, never a
    garbage token).

    ``sampled=False`` compiles the all-greedy specialization: when no
    active request samples this step, the accept needs NO threefry
    draws and no top-k sort — ``argmax(masked) == argmax(logits)``
    always (the argmax survives its own top-k mask), and the
    rejected-token residual mask never moves a greedy argmax either —
    so the cheap variant is BIT-IDENTICAL to the full one on greedy
    rows while cutting the accept from ~2x the whole L-row forward to
    noise (measured on CPU). The engine picks the variant per step
    from the active slots' temperatures; both are ladder rungs.
    """
    k = draft_len
    L = k + 1

    def _accept(logits, draft, dlen, force_reject, bases, counts,
                temps, topks, rep, pres, freq, allowed, pcounts,
                prev0):
        B, _, V = logits.shape
        # The logit pipeline (models/decode.py:apply_logit_pipeline),
        # applied to EVERY verify row exactly as the L=1 sampler
        # applies it to its one row — the parity that keeps
        # constrained+spec distribution-preserving (Leviathan's test
        # needs identical target processing) and greedy constrained
        # spec bit-identical to non-spec. Row j's histogram counts the
        # draft tokens before it (a cumsum of one-hots, in-kernel);
        # row j's constraint mask is the FSM row for the state reached
        # through drafts 0..j-1, built host-side (all-ones when
        # unconstrained — the pipeline's where passes raw logits
        # through bit-identically).
        if k > 0:
            oh = jax.nn.one_hot(draft, V, dtype=jnp.int32)
            prefix = jnp.concatenate(
                [jnp.zeros((B, 1, V), jnp.int32),
                 jnp.cumsum(oh, axis=1)], axis=1,
            )
        else:
            prefix = jnp.zeros((B, L, V), jnp.int32)
        counts3 = pcounts[:, None] + prefix
        proc = apply_logit_pipeline(
            logits.reshape(B * L, V), allowed.reshape(B * L, V),
            counts3.reshape(B * L, V),
            jnp.repeat(rep, L), jnp.repeat(pres, L),
            jnp.repeat(freq, L),
        ).reshape(B, L, V)
        if sampled:
            kth = jnp.clip(topks - 1, 0, V - 1)
            sorted_desc = -jnp.sort(-proc, axis=-1)
            thresh = jnp.take_along_axis(
                sorted_desc,
                jnp.broadcast_to(kth[:, None, None], (B, L, 1)),
                axis=-1,
            )
            masked = jnp.where(
                (topks > 0)[:, None, None] & (proc < thresh),
                -jnp.inf, proc,
            )
        else:
            masked = proc  # greedy: the mask cannot move an argmax
        safe_t = jnp.where(temps > 0, temps, 1.0)
        if k > 0:
            j_idx = jnp.arange(k)[None, :]
            pred = jnp.argmax(masked[:, :k], axis=-1)  # (B, k)
            acc = pred == draft
            if sampled:
                probs = jax.nn.softmax(
                    masked[:, :k] / safe_t[:, None, None], axis=-1
                )
                p_d = jnp.take_along_axis(
                    probs, draft[..., None], axis=-1
                )[..., 0]
                cj = counts[:, None] + j_idx  # token index per row
                tok_keys = jax.vmap(
                    jax.vmap(jax.random.fold_in, in_axes=(None, 0)),
                    in_axes=(0, 0),
                )(bases, cj)  # (B, k, 2)
                u = jax.vmap(jax.vmap(
                    lambda kk: jax.random.uniform(
                        jax.random.fold_in(kk, _SPEC_ACCEPT_SALT)
                    )
                ))(tok_keys)
                acc = jnp.where((temps <= 0)[:, None], acc, u < p_d)
            acc = (
                acc & (j_idx < dlen[:, None])
                & jnp.logical_not(force_reject)
            )
            # accepted prefix length: leading run of accepted rows
            a = jnp.cumprod(acc.astype(jnp.int32), axis=1).sum(axis=1)
            d_rej = jnp.take_along_axis(
                draft, jnp.clip(a, 0, k - 1)[:, None], axis=1
            )[:, 0]
        else:
            a = jnp.zeros_like(counts)
            d_rej = jnp.zeros_like(counts)
        # correction/bonus token from row a: on rejection, the residual
        # distribution (one-hot drafter => target with the rejected
        # token removed, renormalized); greedy argmax is unchanged by
        # that mask (the rejected token was not the argmax)
        row_logits = jnp.take_along_axis(
            masked, a[:, None, None], axis=1
        )[:, 0]  # (B, V)
        if sampled:
            rejected = a < dlen
            corr_logits = jnp.where(
                rejected[:, None]
                & (jnp.arange(V)[None, :] == d_rej[:, None]),
                -jnp.inf, row_logits,
            )
            corr_keys = jax.vmap(jax.random.fold_in)(bases, counts + a)
            drawn = jax.vmap(
                lambda kk, lg: jax.random.categorical(kk, lg)
            )(corr_keys, corr_logits / safe_t[:, None])
            corr = jnp.where(
                temps <= 0, jnp.argmax(corr_logits, axis=-1), drawn
            ).astype(jnp.int32)
        else:
            del d_rej
            corr = jnp.argmax(row_logits, axis=-1).astype(jnp.int32)
        jL = jnp.arange(L)[None, :]
        draft_pad = (
            jnp.pad(draft, ((0, 0), (0, 1))) if k > 0
            else jnp.zeros((B, L), jnp.int32)
        )
        tokens_out = jnp.where(
            jL < a[:, None], draft_pad,
            jnp.where(jL == a[:, None], corr[:, None], 0),
        ).astype(jnp.int32)
        # finite guard over each slot's USED rows only (rows past its
        # draft length computed garbage by design)
        finite_rows = jnp.isfinite(logits).all(axis=-1)
        ok = (finite_rows | (jL > dlen[:, None])).all(axis=1)
        # per-row logprob echo over the distribution each row's token
        # came from (processed + top-k'd + temperature-scaled, same
        # surface as the L=1 sampler). The greedy rung skips the top-k
        # masking (it cannot move an argmax), so its echo ignores
        # top_k — logprob comparisons on the greedy rung hold with
        # top_k off (documented in the README runbook).
        scaled = masked / safe_t[:, None, None]
        lp = jax.nn.log_softmax(scaled, axis=-1)
        chosen_lp = jnp.take_along_axis(
            lp, tokens_out[..., None], axis=-1
        )[..., 0]  # (B, L)
        top_lp, top_ids = jax.lax.top_k(lp, lp_k)  # (B, L, lp_k)
        qv = None
        if quality:
            # per-row quality tail over the SAME surfaces as the L=1
            # sampler (lp for entropy, proc for margin). Row j's
            # "previous token" is row j-1's emitted token; row 0's is
            # the slot's last emitted token (``prev0``, the verify
            # block's row-0 input). Rows past the accepted prefix
            # compute garbage the host never reads.
            prev_chain = jnp.concatenate(
                [prev0[:, None], tokens_out[:, :-1]], axis=1
            )
            # the sampled rung's top-k threshold sort already ranks
            # proc — reuse its head for the margin (greedy rungs have
            # no sort on hand and fall back to top_k inside)
            qv = quality_vector(
                lp, proc, tokens_out, prev_chain,
                top2=(sorted_desc[..., :2]
                      if sampled and V >= 2 else None),
            )
        return (tokens_out, (a + 1).astype(jnp.int32), ok,
                chosen_lp, top_ids, top_lp, qv)

    # Every per-slot scalar operand rides ONE packed (B, 3L+k+10)
    # int32 array and every host-consumed result ONE stacked
    # (B, 2+2L+2L*lp_k) int32 array: ten separate host->device
    # conversions plus three device->host fetches per iteration were
    # the dominant slice of the spec step's host overhead on CPU
    # (measured ~1.4 ms/iteration — more than the whole fused device
    # program). Column layout (static slices below): tokens |
    # positions | write targets (cache row or physical page) | draft |
    # dlen | counts | topks | PRNG base (2 cols, bitcast uint32) |
    # temperature (bitcast f32) | force-reject flag | repetition |
    # presence | frequency penalties (bitcast f32). The constraint
    # masks (B, L, V) and penalty histograms (B, V) are their own
    # runtime-array operands (cached inert constants when no active
    # slot needs the pipeline).
    def _unpack(ints):
        c = 3 * L + k
        tokens = ints[:, 0:L]
        pos = ints[:, L:2 * L]
        targets = ints[:, 2 * L:3 * L]
        draft = ints[:, 3 * L:c]
        dlen = ints[:, c]
        counts = ints[:, c + 1]
        topks = ints[:, c + 2]
        bases = jax.lax.bitcast_convert_type(
            ints[:, c + 3:c + 5], jnp.uint32
        )
        temps = jax.lax.bitcast_convert_type(
            ints[:, c + 5], jnp.float32
        )
        force_reject = ints[0, c + 6] > 0
        pens = jax.lax.bitcast_convert_type(
            ints[:, c + 7:c + 10], jnp.float32
        )
        return (tokens, pos, targets, draft, dlen, counts, topks,
                bases, temps, force_reject, pens)

    def _pack_out(toks, n_emit, ok, chosen_lp, top_ids, top_lp, qv):
        B = toks.shape[0]
        cols = [
            toks, n_emit[:, None], ok.astype(jnp.int32)[:, None],
            jax.lax.bitcast_convert_type(chosen_lp, jnp.int32),
            top_ids.astype(jnp.int32).reshape(B, L * lp_k),
            jax.lax.bitcast_convert_type(
                top_lp, jnp.int32
            ).reshape(B, L * lp_k),
        ]
        if qv is not None:
            # quality tail appended LAST (every existing echo offset
            # stays valid): entropy | margin | repeat, L columns each
            cols.append(jax.lax.bitcast_convert_type(
                jnp.moveaxis(qv, -1, 1).reshape(B, 3 * L), jnp.int32
            ))
        return jnp.concatenate(cols, axis=1)

    donate = jax.default_backend() != "cpu"
    if page_size > 0:

        def _spec_step(params, ints, cache, page_tables, allowed,
                       pcounts):
            (tokens, pos, write_pages, draft, dlen, counts, topks,
             bases, temps, force_reject, pens) = _unpack(ints)
            logits, new_cache = forward_decode_spec_paged(
                params, tokens, pos, cache, page_tables, write_pages,
                cfg, rope_len=rope_len, batched=batched,
            )
            out = _accept(
                logits.astype(jnp.float32), draft, dlen, force_reject,
                bases, counts, temps, topks,
                pens[:, 0], pens[:, 1], pens[:, 2], allowed, pcounts,
                tokens[:, 0],
            )
            return _pack_out(*out), new_cache

        return jax.jit(
            _spec_step, donate_argnums=(2,) if donate else ()
        )

    def _spec_step(params, ints, cache, allowed, pcounts):
        (tokens, pos, row_target, draft, dlen, counts, topks,
         bases, temps, force_reject, pens) = _unpack(ints)
        logits, new_cache = forward_decode_spec(
            params, tokens, pos, cache, cfg, row_target,
            rope_len=rope_len, batched=batched,
        )
        out = _accept(
            logits.astype(jnp.float32), draft, dlen, force_reject,
            bases, counts, temps, topks,
            pens[:, 0], pens[:, 1], pens[:, 2], allowed, pcounts,
            tokens[:, 0],
        )
        return _pack_out(*out), new_cache

    return jax.jit(_spec_step, donate_argnums=(2,) if donate else ())


def _penalties_on(p) -> bool:
    """Whether a request's SamplingParams engage the histogram side of
    the logit pipeline (repetition/presence/frequency)."""
    return (
        p.repetition_penalty != 1.0
        or p.presence_penalty != 0.0
        or p.frequency_penalty != 0.0
    )


class ServingEngine:
    """Continuous-batching engine over one model's params.

    Drive it either synchronously — ``submit()`` then ``run()`` /
    ``generate()`` — or one :meth:`step` at a time (what the background
    thread in serving/server.py does). Not thread-safe by itself; wrap
    it in :class:`serving.server.EngineRunner` for concurrent callers.
    """

    def __init__(self, params: dict, cfg: ModelConfig,
                 serving: Optional[ServingConfig] = None,
                 registry: Optional[Registry] = None,
                 tracer=None, spec_drafter=None,
                 vocab: Optional[Sequence[str]] = None):
        self.params = params
        self.serving = serving or ServingConfig()
        # serving-side overrides: serve a checkpoint with the fused
        # decode kernel / quantized KV without editing its model config
        if self.serving.decode_attention_impl:
            cfg = cfg.replace(
                decode_attention_impl=self.serving.decode_attention_impl
            )
        if self.serving.kv_cache_dtype:
            cfg = cfg.replace(kv_cache_dtype=self.serving.kv_cache_dtype)
        self.cfg = cfg
        self.max_total = self.serving.resolved_max_seq_len(cfg)
        # Paged KV cache (serving/pages.py): device KV lives in fixed
        # pages mapped through per-slot page tables; admission keys on
        # free pages; a radix tree shares cached prompt prefixes.
        self._paged = self.serving.paged()
        self._pages: Optional[PagePool] = None
        # Host-RAM page tier (serving/host_tier.py): evicted full radix
        # pages demote there instead of vanishing; admissions matching
        # a demoted prefix promote it back with a copy, never a
        # recompute. The tier also holds preempted requests' stashes.
        self._tier = None
        # request_id -> host-side decode snapshot of a PREEMPTED
        # request (its KV pages live in a tier stash under the same
        # id); consumed by the bit-exact resume path in _admit_paged
        self._resume: dict = {}
        # (slot, snapshot) pairs resumed by THIS step's admission gate;
        # step() restores their decode state after plan() commits
        self._resumed: list = []
        if self._paged:
            ps = self.serving.kv_page_size
            pool = self.serving.resolved_pool_pages(cfg)  # checks ps | M
            if self.serving.tiered():
                from differential_transformer_replication_tpu.serving.host_tier import (
                    HostTier,
                )

                self._tier = HostTier(
                    budget_bytes=self.serving.host_tier_bytes
                )
            self._pages = PagePool(
                page_size=ps,
                pages_per_slot=cfg.block_size // ps,
                num_slots=self.serving.num_slots,
                total_pages=pool + 1,  # + the reserved trash page
                prefix_cache=self.serving.prefix_cache,
                tier=self._tier,
            )
        # Speculative decoding (serving/spec.py): the drafter proposes
        # up to spec_draft_len tokens per slot per iteration; the
        # target verifies all of them in ONE fused k+1-row pool step.
        # On the contiguous path the pool carries one extra TRASH ROW
        # (batch index num_slots) that absorbs the write-redirected
        # rejected/invalid verify rows — the paged path's trash page
        # already does that job, so its pool shape is unchanged.
        self._spec_k = (
            self.serving.spec_draft_len
            if self.serving.spec_enabled() else 0
        )
        self._rows = self.serving.num_slots + (
            1 if self._spec_k and not self._paged else 0
        )
        self._drafter = None
        self._spec_fn = None
        self._spec_window = cfg.block_size
        if self._spec_k:
            from differential_transformer_replication_tpu.serving.spec import (
                build_drafter,
            )

            self._drafter = build_drafter(
                self.serving, cfg, self.max_total, drafter=spec_drafter
            )
            dw = getattr(self._drafter, "window", None)
            if dw is not None:
                self._spec_window = min(self._spec_window, dw())
        # structured decoding (serving/constrain.py): the engine-level
        # compiled-constraint cache, the request_id -> (cache key,
        # token FSM) map of in-flight constrained requests, and the
        # id -> string vocabulary the compiler walks. lp_k is the
        # compile-time logprob echo width — per-request logprobs <= lp_k
        # ride as host-side truncation, never a new trace.
        self._vocab = tuple(vocab) if vocab is not None else None
        self._lp_k = min(self.serving.max_logprobs, cfg.vocab_size)
        # quality telemetry (obs/quality.py): a STATIC of the jitted
        # sampler/verify closures like lp_k — on, they append the
        # in-jit quality tail; off, they compile the exact
        # pre-telemetry trace (bit-identical output by construction)
        self._quality = bool(self.serving.quality_telemetry)
        self._constraint_cache = ConstraintCache(
            self.serving.constraint_cache_entries
        )
        self._constraints: dict = {}
        # inert pipeline operands (all-ones masks / zero histograms) by
        # batch shape, held as device constants so unconstrained
        # traffic pays no per-step (B, V) host build or transfer
        self._inert: dict = {}
        if self._spec_k:
            # both accept variants of the k rung (greedy-specialized /
            # full sampled) — the step picks per iteration from the
            # active slots' temperatures; together with the L=1 step
            # they are the WHOLE fixed compile ladder
            self._spec_fn = {
                s: _build_spec_step_fns(
                    cfg, self.max_total, self._spec_k, sampled=s,
                    batched=self.serving.spec_verify == "batched",
                    page_size=(
                        self.serving.kv_page_size if self._paged else 0
                    ),
                    num_pages=(
                        self._pages.total_pages if self._paged else 0
                    ),
                    lp_k=self._lp_k,
                    quality=self._quality,
                )
                for s in (False, True)
            }
            self._drafter_crashes_seen = 0
        (self._prefill_fn, self._decode_fn, self._sample_fn,
         self._copy_fn, self._extract_fn, self._inject_fn) = _build_step_fns(
            cfg, self.max_total,
            page_size=self.serving.kv_page_size if self._paged else 0,
            num_pages=self._pages.total_pages if self._paged else 0,
            lp_k=self._lp_k,
            quality=self._quality,
        )
        self.cache = (
            init_cache_paged(cfg, self._pages.total_pages,
                             self.serving.kv_page_size)
            if self._paged else init_cache(cfg, self._rows)
        )
        self.scheduler = Scheduler(
            self.serving,
            on_retire=(
                self._on_retire
                if (self._paged or self._spec_k) else None
            ),
            on_preempt=(
                self._preempt_slot if self._tier is not None else None
            ),
        )
        self._next_id = 0
        self._base_keys: dict = {}  # request_id -> np (2,) uint32 PRNG base
        # outputs produced by a step() that later RAISED: the finished/
        # shed requests were already retired from the scheduler, so they
        # would be unreachable after the crash (neither slot-holding nor
        # queued) — the buffer keeps them deliverable (take_finished)
        self._finished_prior: List[RequestOutput] = []
        # Telemetry (obs/): per-engine registry by default so tests and
        # multi-engine processes never cross-contaminate; the serving
        # server exposes it at GET /metrics. The tracer (obs/spans.py)
        # is a shared no-op unless a Chrome-trace path was requested.
        self.registry = registry or Registry()
        self.tracer = tracer or NOOP_TRACER
        # request-lifecycle instrumentation (admit/first_token/finish
        # instants, per-request lifetime spans, trace-context stamping)
        # is gated on a REAL tracer so the tracing-off hot path pays
        # only the existing no-op span calls — nothing per token
        self._tracing = self.tracer is not NOOP_TRACER
        # stats: dict-compatible view over registry counters (the
        # /health JSON keeps its shape; /metrics reads the same values)
        self.stats = StatsMap(self.registry, _STAT_SPEC)
        self._finished_counter = self.registry.counter(
            "serving_requests_finished_total",
            "Retired requests by finish reason.", labelnames=("reason",),
        )
        self._ttft_hist = self.registry.histogram(
            "serving_ttft_seconds",
            "Time from submit to first generated token.",
        )
        self._itl_hist = self.registry.histogram(
            "serving_itl_seconds",
            "Inter-token latency between consecutive generated tokens.",
        )
        self._queue_wait_hist = self.registry.histogram(
            "serving_queue_wait_seconds",
            "Time from submit to first prefill chunk (slot admission).",
        )
        self._step_hist = self.registry.histogram(
            "serving_engine_step_seconds",
            "Wall time of one engine iteration (schedule+prefill+decode).",
        )
        self._slot_gauge = self.registry.gauge(
            "serving_slot_occupancy",
            "KV slots currently held by in-flight requests.",
        )
        self.registry.gauge(
            "serving_slots", "Size of the fixed KV slot pool."
        ).set(self.serving.num_slots)
        self._kv_gauge = self.registry.gauge(
            "serving_kv_utilization",
            "Fraction of pooled KV positions holding live sequence state.",
        )
        self._queue_gauge = self.registry.gauge(
            "serving_queue_depth", "Requests waiting for a slot."
        )
        # priority-class telemetry: per-class queue depths plus the
        # per-class TTFT/ITL series obs/slo.py's per-class objectives
        # evaluate — a saturating batch class cannot hide an
        # interactive-class SLO violation inside an unlabeled series
        self._queue_class_gauge = self.registry.gauge(
            "serving_queue_depth_by_class",
            "Requests waiting for a slot, by priority class.",
            labelnames=("priority",),
        )
        self._class_ttft_hist = self.registry.histogram(
            "serving_class_ttft_seconds",
            "Time from submit to first generated token, by priority "
            "class.",
            labelnames=("priority",),
        )
        self._class_itl_hist = self.registry.histogram(
            "serving_class_itl_seconds",
            "Inter-token latency between consecutive generated tokens, "
            "by priority class.",
            labelnames=("priority",),
        )
        # quantization-aware capacity telemetry: the per-slot HBM cost of
        # KV state (int8 roughly halves it vs bf16 — the dashboards'
        # capacity-win signal) and the active storage dtype as a labeled
        # identity gauge
        self.registry.gauge(
            "serving_kv_cache_bytes_per_slot",
            "HBM bytes of pooled KV-cache state per slot "
            "(includes int8 scale planes when quantized).",
        ).set(
            sum(leaf.nbytes for layer in self.cache
                for leaf in layer.values())
            // self._rows
        )
        self.registry.gauge(
            "serving_kv_cache_dtype",
            "Active KV-cache storage dtype (constant 1; the identity "
            "rides the label).",
            labelnames=("dtype",),
        ).set(1, dtype=kv_store_dtype(cfg))
        # paged-pool telemetry (serving/pages.py): point-in-time page
        # gauges plus the monotonic prefix-cache counters, mirrored
        # from the pool's locked host state on every gauge refresh —
        # scraped at /metrics, aggregated fleet-wide at /fleet/metrics,
        # snapshotted into /health as "kv_pages"
        if self._pages is not None:
            st = self._pages.stats()
            self.registry.gauge(
                "serving_kv_pages_total",
                "Physical KV pages in the pool (trash page excluded).",
            ).set(st["total"])
            self._pages_free_gauge = self.registry.gauge(
                "serving_kv_pages_free",
                "KV pages currently unallocated.",
            )
            self._pages_cached_gauge = self.registry.gauge(
                "serving_kv_pages_cached",
                "KV pages held by the radix prefix cache.",
            )
            self._cow_forks_counter = self.registry.counter(
                "serving_kv_pages_cow_forks_total",
                "Copy-on-write forks of shared prefix pages.",
            )
            self._prefix_hits_counter = self.registry.counter(
                "serving_prefix_cache_hits_total",
                "Admissions that reused a cached prompt prefix.",
            )
            self._prefix_misses_counter = self.registry.counter(
                "serving_prefix_cache_misses_total",
                "Admissions with no cached prefix to reuse.",
            )
            self._prefix_evictions_counter = self.registry.counter(
                "serving_prefix_cache_evictions_total",
                "Cached prefix pages LRU-evicted under page pressure.",
            )
            self.registry.gauge(
                "serving_kv_page_bytes",
                "HBM bytes per physical KV page across all layers "
                "(int8-aware: values + fp32 scale planes).",
            ).set(page_bytes(cfg, self.serving.kv_page_size))
            self._tier_prefix_hits_counter = self.registry.counter(
                "serving_host_tier_prefix_hits_total",
                "Admissions whose prefix match extended into the "
                "host tier (promoted, never recomputed).",
            )
        # host-tier telemetry: byte/entry gauges plus the tier's locked
        # counters, mirrored on every gauge refresh (the page-pool
        # pattern) — the "Serving under memory pressure" runbook's
        # dashboard surface
        if self._tier is not None:
            self.registry.gauge(
                "serving_host_tier_budget_bytes",
                "Configured host-RAM byte budget of the KV page tier.",
            ).set(self.serving.host_tier_bytes)
            self._tier_bytes_gauge = self.registry.gauge(
                "serving_host_tier_bytes",
                "Host bytes currently held by the KV page tier "
                "(cached prefixes + pinned preemption stashes).",
            )
            self._tier_entries_gauge = self.registry.gauge(
                "serving_host_tier_entries",
                "Demoted prefix pages currently cached in the host tier.",
            )
            self._tier_stashes_gauge = self.registry.gauge(
                "serving_host_tier_stashes",
                "Preempted requests with KV stashed in the host tier.",
            )
            self._tier_hits_counter = self.registry.counter(
                "serving_host_tier_hits_total",
                "Host-tier prefix lookups that hit a demoted page.",
            )
            self._tier_misses_counter = self.registry.counter(
                "serving_host_tier_misses_total",
                "Host-tier prefix lookups that missed.",
            )
            self._tier_evictions_counter = self.registry.counter(
                "serving_host_tier_evictions_total",
                "Cached tier pages LRU-evicted under the byte budget.",
            )
            self._tier_corrupt_counter = self.registry.counter(
                "serving_host_tier_corrupt_total",
                "Tier page images whose CRC32 verify failed (dropped "
                "and recomputed, never injected).",
            )
        # speculative-decoding telemetry: the aggregate proposed/
        # accepted counters ride _STAT_SPEC (so /health and /metrics
        # can never disagree); the acceptance-rate gauge and the
        # drafter identity/footprint land here. All on /metrics, all
        # summed/labeled through the fleet aggregation like every
        # other serving series.
        self._spec_accept_gauge = None
        if self._spec_k:
            self._spec_accept_gauge = self.registry.gauge(
                "serving_spec_acceptance_rate",
                "Accepted / proposed draft tokens (cumulative).",
            )
            self.registry.gauge(
                "serving_spec_draft_len",
                "Compiled draft-length rung k of the fused verify step.",
            ).set(self._spec_k)
            self.registry.gauge(
                "serving_spec_mode",
                "Active speculative-decoding drafter (constant 1; the "
                "identity rides the label).",
                labelnames=("mode",),
            ).set(1, mode=self.serving.spec_mode)
            drafter_bytes = getattr(self._drafter, "bytes_total", None)
            if drafter_bytes is not None:
                # the model drafter's own KV pool is HBM the operator
                # must account beside the target's pages/slots (README
                # "Speculative decoding" runbook's equal-HBM recipe)
                self.registry.gauge(
                    "serving_spec_drafter_kv_bytes",
                    "HBM bytes held by the drafter's own KV slot pool.",
                ).set(drafter_bytes())
        # structured-decoding telemetry: in-flight constrained requests
        # plus the compile cache's locked counters, mirrored into the
        # registry on every gauge refresh (the page-pool pattern) —
        # scraped at /metrics, aggregated fleet-wide, snapshotted into
        # /health as "constraints"
        self._constrained_gauge = self.registry.gauge(
            "serving_constrained_requests_active",
            "In-flight requests decoding under a compiled constraint.",
        )
        self._ccache_entries_gauge = self.registry.gauge(
            "serving_constraint_cache_entries",
            "Compiled constraint FSMs currently cached.",
        )
        self._ccache_bytes_gauge = self.registry.gauge(
            "serving_constraint_cache_bytes",
            "Host bytes held by cached constraint FSM tables.",
        )
        self._ccache_hits_counter = self.registry.counter(
            "serving_constraint_cache_hits_total",
            "Constraint compiles avoided by the FSM cache.",
        )
        self._ccache_misses_counter = self.registry.counter(
            "serving_constraint_cache_misses_total",
            "Constraint specs compiled from scratch.",
        )
        # model-quality telemetry (obs/quality.py): the in-jit quality
        # tail's host-side aggregation — per-token entropy/margin
        # histograms on the fixed fingerprint bin ladders, per-layer
        # effective-lambda gauges (the paper's central quantity, live
        # from the SERVING params), the PSI drift score against an
        # optional recorded fingerprint, and the constraint-validity
        # rate the canary judge's quality axis reads. The accumulator
        # dict and fault flag exist unconditionally (cheap pops on
        # every retire path); metrics + monitor only when
        # ServingConfig.quality_telemetry is on.
        self._q_acc: dict = {}
        self._q_force_nan = False
        self._q_constraint_total = 0
        self._q_constraint_bad = 0
        self._quality_monitor = None
        self._lambda_gauge = None
        self._lambda_summary: dict = {}
        if self._quality:
            ref = None
            if self.serving.quality_fingerprint:
                # a bad reference path must fail at BUILD, not judge
                # garbage drift at rollout time
                ref = load_fingerprint(self.serving.quality_fingerprint)
            self._quality_monitor = QualityMonitor(reference=ref)
            self._q_entropy_hist = self.registry.histogram(
                "serving_token_entropy",
                "Sampled-distribution entropy (nats) per emitted token.",
                buckets=ENTROPY_BINS,
            )
            self._q_margin_hist = self.registry.histogram(
                "serving_logit_margin",
                "Top-1 vs top-2 processed-logit margin per emitted "
                "token.",
                buckets=MARGIN_BINS,
            )
            self._q_drift_gauge = self.registry.gauge(
                "serving_quality_drift",
                "Max PSI drift of the live entropy/margin sketches vs "
                "the recorded reference fingerprint (0 = no reference, "
                "thin evidence, or no drift).",
            )
            self._q_validity_gauge = self.registry.gauge(
                "serving_constraint_validity_rate",
                "Fraction of finished constrained requests that did "
                "NOT dead-end (1.0 until any constrained request "
                "finishes).",
            )
            self._q_validity_gauge.set(1.0)
            self._lambda_gauge = self.registry.gauge(
                "serving_lambda_mean",
                "Per-layer effective differential-attention lambda of "
                "the serving params (head/term mean; absent for the "
                "control family).",
                labelnames=("layer",),
            )
            self._refresh_lambda_gauges()
        # Continuous on-device profiling (obs/device_profile.py): every
        # profile_every engine iterations, wrap ONE iteration in a
        # jax.profiler capture, parse it off-loop, and publish device_*
        # gauges into this same registry (scraped at /metrics), JSONL
        # rows under the spool, and a stitchable device-lane trace.
        # Uncaptured iterations pay one integer compare; the capture
        # wraps already-compiled steps, so the decode compile count
        # stays pinned at 1 (tests/test_device_profile.py).
        self._device_prof = None
        if self.serving.profile_every > 0:
            from differential_transformer_replication_tpu.obs.device_profile import (
                DeviceProfileSampler,
            )

            self._device_prof = DeviceProfileSampler(
                every=self.serving.profile_every,
                spool_dir=self.serving.profile_dir,
                registry=self.registry,
                tracer=self.tracer,
                process="serving",
            )

    # -- submission ---------------------------------------------------

    def submit(self, prompt: Sequence[int],
               params: Optional[SamplingParams] = None,
               deadline: Optional[float] = None,
               trace: Optional[TraceContext] = None, **kw) -> int:
        """Queue one request; returns its request_id. ``kw`` are
        SamplingParams fields (max_new_tokens, temperature, top_k, seed,
        eos_token_id). ``deadline`` is an ABSOLUTE ``time.perf_counter``
        timestamp after which the engine stops working on the request
        (shed at admission / retired mid-decode, ``finish_reason ==
        "deadline"``); None applies ``ServingConfig.default_deadline_s``
        when set. ``trace`` is the request's cross-process trace
        context (obs/trace.py): host-side only, stamped onto the
        admit/first_token/finish instants and the request-lifetime span
        when tracing is on, echoed as ``RequestOutput.trace_id`` —
        never touching the jitted closures, so tracing costs zero
        recompiles. Raises ValueError when the request cannot fit the
        engine's static shapes (see module docstring on family limits).
        """
        rid = self._next_id
        self._next_id += 1
        req = Request.make(rid, prompt, params, **kw)
        M = self.cfg.block_size
        p = np.asarray(req.prompt, np.int32)
        if self.cfg.model == "diff":
            if p.shape[0] + req.params.max_new_tokens > M:
                raise ValueError(
                    f"prompt ({p.shape[0]}) + max_new_tokens "
                    f"({req.params.max_new_tokens}) exceeds block_size ({M}) "
                    "and the diff family's learned absolute position table "
                    "cannot roll with a KV cache (models/decode.py)"
                )
        else:
            if p.shape[0] > M:
                p = p[-M:]  # the reference's own crop (control.py:165)
            if p.shape[0] + req.params.max_new_tokens > self.max_total:
                raise ValueError(
                    f"cropped prompt ({p.shape[0]}) + max_new_tokens "
                    f"({req.params.max_new_tokens}) exceeds the engine's "
                    f"max_seq_len ({self.max_total}); build the engine with "
                    "a larger ServingConfig.max_seq_len"
                )
        if self._pages is not None:
            # a request whose worst case exceeds the whole pool can
            # NEVER be admitted — fail typed at submit instead of
            # parking it at the queue head forever
            need = self._pages.pages_needed(
                int(p.shape[0]), req.params.max_new_tokens
            )
            if need > self._pages.capacity:
                self.stats.inc("rejected")
                err = PagePoolExhaustedError(
                    f"request needs {need} KV pages but the pool holds "
                    f"{self._pages.capacity}; raise "
                    "ServingConfig.kv_pool_pages or lower "
                    "max_new_tokens"
                )
                err.retriable = False
                raise err
        # structured decoding: compile (or cache-hit) the constraint
        # BEFORE the scheduler sees the request — a malformed spec
        # fails typed (ConstraintCompileError -> HTTP 400) with the
        # engine untouched: no queue entry, no key chain, no slot
        ckey = None
        cfsm = None
        if req.params.constrained:
            eos = (
                req.params.eos_token_id
                if req.params.eos_token_id is not None
                else self.serving.eos_token_id
            )
            ckey = spec_key(req.params, eos)
            if self._vocab is None:
                self.stats.inc("rejected")
                raise ConstraintCompileError(
                    "constrained request but the engine was built "
                    "without a vocabulary (pass vocab= — the id->string "
                    "table the FSM compiler walks)"
                )
            try:
                cfsm = self._constraint_cache.acquire(ckey, self._vocab)
            except ConstraintCompileError:
                self.stats.inc("rejected")
                raise
        now = time.perf_counter()
        if deadline is None and self.serving.default_deadline_s > 0:
            deadline = now + self.serving.default_deadline_s
        # admission bound next (scheduler.submit raises QueueFullError
        # when the wait queue is at ServingConfig.max_queue_len) — a
        # rejected request must leave no key-chain or constraint
        # reference behind
        try:
            self.scheduler.submit(req, p, now, deadline or 0.0,
                                  trace=trace)
        except Exception:
            if ckey is not None:
                self._constraint_cache.release(ckey)
            self.stats.inc("rejected")
            raise
        if ckey is not None:
            self._constraints[rid] = (ckey, cfsm)
        self._base_keys[rid] = np.asarray(
            jax.random.PRNGKey(req.params.seed), np.uint32
        )
        return rid

    def cancel(self, request_id: int) -> bool:
        """Abandon an in-flight request: dropped from the wait queue, or
        its slot retired so the KV rows return to the pool. Without this
        a caller that times out leaves the engine decoding to completion
        for nobody — the slot leak serving/server.py's timeout path used
        to have. Returns False when the request is unknown or already
        finished (its output was, or is about to be, delivered)."""
        if request_id not in self._base_keys:
            return False
        self.scheduler.cancel(request_id)
        del self._base_keys[request_id]
        self._drop_constraint(request_id)
        self._drop_resume(request_id)
        self._q_acc.pop(request_id, None)
        self.stats.inc("cancelled")
        self._finished_counter.inc(reason="cancelled")
        return True

    def _drop_constraint(self, request_id: int) -> None:
        """Release a request's compiled-FSM reference on EVERY path
        that forgets its key chain (finish, cancel, shed, expire,
        crash) — a leaked reference would pin the cache entry forever."""
        ent = self._constraints.pop(request_id, None)
        if ent is not None:
            self._constraint_cache.release(ent[0])

    # -- one engine iteration -----------------------------------------

    def has_work(self) -> bool:
        return self.scheduler.has_work()

    def queue_len(self) -> int:
        """Requests waiting for a slot (admission-queue depth)."""
        return self.scheduler.queue_len()

    def step(self) -> List[RequestOutput]:
        """Deadline shed -> admit -> prefill (budgeted) -> batched
        decode. Returns the requests that finished THIS iteration
        (including ones retired with ``finish_reason == "deadline"``)."""
        if not self.scheduler.has_work():
            out, self._finished_prior = self._finished_prior, []
            return out
        iteration = self.stats["iterations"]
        t_step = time.perf_counter()
        # non-due iterations pay one integer compare; a due one opens a
        # device-profile capture window around exactly this iteration
        capturing = (
            self._device_prof is not None
            and self._device_prof.maybe_begin(iteration)
        )
        faults.serve_fire(iteration)
        if self._quality:
            # chaos drills for the drift detector (utils/faults.py):
            # quality_drift perturbs the live params — logits stay
            # FINITE, so requests keep succeeding and latency is flat;
            # only the quality axis can catch it. quality_nan poisons
            # this iteration's telemetry tail host-side — it must
            # degrade to "no signal", never crash the step or judge.
            if faults.quality_drift_at(iteration):
                self._apply_quality_drift()
            self._q_force_nan = faults.quality_nan_at(iteration)
        # build into the survives-an-exception buffer: a request that
        # finishes (or is deadline-shed) early in this step and is
        # already retired must still reach its caller when a LATER part
        # of the same step crashes (see take_finished)
        finished = self._finished_prior

        if self._pages is not None and faults.page_exhaust_at(iteration):
            # chaos hook: the next admission plan raises the typed
            # PagePoolExhaustedError — proving the 503 shed path
            self._pages.force_exhaust()

        with self.tracer.span("schedule", iteration=iteration):
            # deadline enforcement, both placements, BEFORE device work:
            # expired queue entries never get a slot, expired slots
            # return their KV rows to the pool instead of decoding for
            # nobody
            now = time.perf_counter()
            for req, prompt, t_submit, _dl, trace in (
                self.scheduler.shed_expired(now)
            ):
                finished.append(
                    self._expire_queued(req, prompt, t_submit, now, trace)
                )
            for slot in self.scheduler.expired_slots(now):
                finished.append(self._finish(slot, "deadline", now=now))
            admit = None
            if self._pages is not None:
                # paged admission keys on FREE PAGES, not slots: the
                # gate plans each head-of-line request against the
                # radix cache + page pool (serving/pages.py) before the
                # scheduler commits a slot to it
                admit = (
                    lambda slot, entry: self._admit_paged(
                        slot, entry, iteration, finished
                    )
                )
            chunks = self.scheduler.plan(admit=admit)

        if self._resumed:
            # requests swapped back in by this plan's admission gate:
            # restore the host-side decode state snapshotted at
            # preemption — the device KV was re-injected bit-exact
            # above, so generation continues as if never interrupted
            # (pinned by tests/test_tiering.py). plan() committed the
            # slot as a fresh PREFILL with filled == prompt_len, so no
            # prefill chunks were planned for it.
            for slot, snap in self._resumed:
                slot.generated = list(snap["generated"])
                slot.token_times = list(snap["token_times"])
                slot.first_token_time = snap["first_token_time"]
                slot.filled = snap["filled"]
                slot.cached_len = snap["cached_len"]
                slot.spec_proposed = snap["spec_proposed"]
                slot.spec_accepted = snap["spec_accepted"]
                slot.prompt_ids = snap["prompt_ids"]
                slot.penalty_counts = snap["penalty_counts"]
                slot.token_logprobs = snap["token_logprobs"]
                slot.top_logprobs = snap["top_logprobs"]
                ent = self._constraints.get(slot.request.request_id)
                if ent is not None:
                    # attach the FSM directly — _slot_fsm's lazy path
                    # would RESET the cursor to the FSM's start state
                    slot.constraint = ent[1]
                    slot.fsm_state = snap["fsm_state"]
                slot.state = ACTIVE
                self._resume.pop(slot.request.request_id, None)
            self._resumed = []

        if chunks:
            with self.tracer.span(
                "prefill", iteration=iteration, chunks=len(chunks)
            ):
                self._run_prefill(chunks, finished)

        if faults.serve_corrupt_at(iteration):
            self._corrupt_one_slot()
        if self._pages is not None and faults.prefix_corrupt_at(iteration):
            self._corrupt_cached_prefix()

        active = self.scheduler.active_slots()
        if self._constraints:
            if faults.constrain_dead_end_at(iteration):
                # chaos hook: poison the first constrained ACTIVE
                # slot's FSM cursor with the dead-end sentinel — the
                # sweep below must retire it typed, never hang or emit
                # a garbage token (the sweep runs BEFORE decode ever
                # consumes the zeroed mask)
                for s in active:
                    if self._slot_fsm(s) is not None:
                        s.fsm_state = -1
                        break
            swept = False
            for s in active:
                fsm = self._slot_fsm(s)
                if fsm is None:
                    continue
                if s.fsm_state >= 0 and fsm.masks[s.fsm_state].any():
                    continue
                # all-zero mask row: nothing this slot could emit.
                # Accepting state = the structure is complete and no
                # EOS was configured — a normal typed completion.
                # Non-accepting = a true dead end (compiled FSMs prune
                # dead states, so only the fault sentinel reaches
                # here) — typed retriable failure, partial output
                # delivered, slot + pages reclaimed through the
                # standard retire path.
                swept = True
                finished.append(self._finish(
                    s,
                    "constraint_complete"
                    if fsm.is_accepting(s.fsm_state)
                    else "constraint_dead_end",
                ))
            if swept:
                active = self.scheduler.active_slots()
        proposals = {}
        if active and self._spec_k:
            proposals = self._collect_proposals(active, iteration)
        if active and proposals:
            self._decode_spec(active, proposals, iteration, finished)
        elif active:
            # the plain L=1 step — also the spec engine's k=0 ladder
            # rung, taken whenever no slot has a proposal this
            # iteration (drafter dry, all slots near their windows,
            # or a rebuilt drafter falling back)
            B = self._rows
            tokens = np.zeros((B,), np.int32)
            pos = np.zeros((B,), np.int32)
            mask = np.zeros((B,), bool)
            for s in active:
                tokens[s.index] = s.generated[-1]
                pos[s.index] = s.prompt_len + len(s.generated) - 1
                mask[s.index] = True
            # the decode step is one batched op over every active slot;
            # its span carries the trace ids it advanced so a stitched
            # timeline shows which requests shared each iteration
            decode_args = {"iteration": iteration, "active": len(active)}
            if self._tracing:
                tids = [
                    s.trace.trace_id for s in active
                    if s.trace is not None
                ]
                if tids:
                    decode_args["trace_ids"] = tids
            with self.tracer.span("decode", **decode_args):
                if self._pages is not None:
                    # page tables + per-row write pages ride the one
                    # jitted step as runtime int32 arrays; inactive
                    # rows write the trash page (masked-merge analog)
                    M = self.cfg.block_size
                    ps = self.serving.kv_page_size
                    tables = self._pages.tables()
                    write_pages = np.zeros((B,), np.int32)
                    for s in active:
                        write_pages[s.index] = tables[
                            s.index, (pos[s.index] % M) // ps
                        ]
                    logits, self.cache = self._decode_fn(
                        self.params, jnp.asarray(tokens),
                        jnp.asarray(pos), self.cache,
                        jnp.asarray(tables), jnp.asarray(write_pages),
                    )
                else:
                    logits, self.cache = self._decode_fn(
                        self.params, jnp.asarray(tokens),
                        jnp.asarray(pos), jnp.asarray(mask), self.cache,
                    )
            with self.tracer.span("sample", iteration=iteration):
                sampled, ok, packed = self._sample_all_slots(logits)
            bad = [s for s in active if not ok[s.index]]
            if bad:
                raise EngineCrashError(
                    f"non-finite logits decoding slot(s) "
                    f"{[s.index for s in bad]} (request(s) "
                    f"{[s.request.request_id for s in bad]}): corrupt "
                    "slot pool or numerically diverged params"
                )
            with self.tracer.span("emit", iteration=iteration):
                now = time.perf_counter()
                self.stats.inc("decode_tokens", len(active))
                for s in active:
                    self._emit(
                        s, int(sampled[s.index]), now, finished,
                        lp=self._lp_echo(s, packed[s.index]),
                        q=(self._quality_echo(packed[s.index])
                           if self._quality else None),
                    )

        if capturing:
            # close the window (blocking on a cache leaf so the
            # iteration's device work is inside it) and hand the trace
            # to the off-loop parse worker
            self._device_prof.end(sync=self.cache[0]["k"])
        self.stats.inc("iterations")
        self._step_hist.observe(time.perf_counter() - t_step)
        self._update_gauges()
        self._finished_prior = []
        return finished

    def _run_prefill(self, chunks, finished: List[RequestOutput]) -> None:
        """Execute one iteration's planned prefill chunks (see
        :meth:`Scheduler.plan`); extracted so the step's tracer span
        brackets exactly the prefill device work."""
        for slot, start, size in chunks:
            if start == slot.cached_len:
                # first chunk actually RUN = the request finally got a
                # slot: the submit->admission interval is the
                # queue-wait component of TTFT. With a radix prefix
                # hit, start lands at cached_len, not 0 — the skipped
                # pages are the near-zero-TTFT win.
                self._queue_wait_hist.observe(
                    time.perf_counter() - slot.submit_time
                )
                if self._tracing:
                    self.tracer.instant(
                        "admit", rid=slot.request.request_id,
                        slot=slot.index, cached=slot.cached_len,
                        **(instant_args(slot.trace)
                           if slot.trace is not None else {}),
                    )
            tokens = jnp.asarray(slot.prompt[start:start + size][None])
            if self._pages is not None:
                logits, self.cache = self._prefill_fn(
                    self.params, self.cache,
                    jnp.asarray(self._pages.table_row(slot.index)),
                    tokens, np.int32(start),
                )
            else:
                logits, self.cache = self._prefill_fn(
                    self.params, self.cache, np.int32(slot.index),
                    tokens, np.int32(start),
                )
            slot.filled = start + size
            self.stats.inc("prefill_tokens", size)
            if slot.filled == slot.prompt_len:
                # prompt complete: the chunk's last-position logits give
                # the first generated token (generate_cached's contract)
                tok, ok, packed = self._sample_rows([slot], logits[None])
                if not ok[0]:
                    raise EngineCrashError(
                        f"non-finite logits prefilling slot {slot.index} "
                        f"(request {slot.request.request_id}): corrupt "
                        "slot pool or numerically diverged params"
                    )
                self._emit(
                    slot, int(tok[0]), time.perf_counter(), finished,
                    lp=self._lp_echo(slot, packed[0]),
                    q=(self._quality_echo(packed[0])
                       if self._quality else None),
                )

    # -- speculative decoding (serving/spec.py) ------------------------

    def _collect_proposals(self, active, iteration: int) -> dict:
        """Ask the drafter for up to k tokens per eligible active slot.

        Eligibility clamps each slot's draft cap so the verify block
        can never (a) overrun the request's max_new_tokens budget (the
        corrected token must still fit), (b) write past the ring
        window — the verify writes positions pos..pos+cap, and a
        rolled-over write would evict keys a rejected row still needs
        visible (the ring "rollback" works precisely because rejected
        positions stay IN-window and invisible) — or (c) exceed the
        drafter's own window. Ineligible slots ride the same step with
        draft length 0 (runtime array — no recompile).
        """
        from differential_transformer_replication_tpu.serving.spec import (
            DraftSlot,
            constrain_proposals,
        )

        if faults.spec_drafter_crash_at(iteration):
            poison = getattr(self._drafter, "poison", None)
            if poison is not None:
                poison()
        infos = []
        for s in active:
            p = s.request.params
            cap = self._spec_k
            if p.draft_len is not None:
                cap = min(cap, p.draft_len)
            pos0 = s.prompt_len + len(s.generated) - 1
            cap = min(cap, p.max_new_tokens - len(s.generated) - 1)
            cap = min(cap, self._spec_window - 1 - pos0)
            if cap <= 0:
                continue
            if s.prompt_ids is None:
                # once per admission, not per iteration: per-element
                # int() of the numpy prompt in the decode hot loop is
                # exactly the host cost class this step budgets
                s.prompt_ids = [int(t) for t in s.prompt]
            infos.append(DraftSlot(
                s.index, s.prompt_ids + s.generated, pos0, cap,
            ))
        if not infos:
            return {}
        props = self._drafter.propose_all(infos)
        if props and self._constraints:
            # drop draft suffixes the slot's FSM can never accept —
            # the verify step would reject them row-for-row anyway
            # (serving/spec.py:constrain_proposals)
            fsms = {}
            for s in active:
                fsm = self._slot_fsm(s)
                if fsm is not None:
                    fsms[s.index] = (fsm, s.fsm_state)
            props = constrain_proposals(props, fsms)
        if not props:
            # the no-proposal signature of a tripped drafter: check
            # (and mirror) the crash counter only on this path so the
            # hot loop never takes the drafter lock twice per step
            crashes = self._drafter.stats()["drafter_crashes_total"]
            if crashes > self._drafter_crashes_seen:
                self.stats.inc(
                    "spec_drafter_crashes",
                    crashes - self._drafter_crashes_seen,
                )
                self._drafter_crashes_seen = crashes
                print(
                    "[serving] spec drafter pool tripped the "
                    "finite-logits guard; rebuilt from params, falling "
                    "back to non-spec decode this iteration",
                    file=sys.stderr,
                )
        return props

    def _decode_spec(self, active, proposals: dict, iteration: int,
                     finished: List[RequestOutput]) -> None:
        """One fused k+1-row verify step over the whole pool: build
        the (B, L) token/position block (row 0 = each slot's last
        emitted token, rows 1..dl its draft), write-redirect rows past
        each slot's draft length to the trash row/page, run the jitted
        step (multi-row forward + fused accept/reject), then emit each
        slot's accepted prefix + corrected token host-side."""
        B = self.serving.num_slots
        k = self._spec_k
        L = k + 1
        M = self.cfg.block_size
        c = 3 * L + k
        # ONE packed int operand (see _build_spec_step_fns._unpack):
        # tokens | positions | write targets | draft | dlen | counts |
        # topks | PRNG base (bitcast) | temperature (bitcast) |
        # force-reject | penalties (bitcast) — a single host->device
        # conversion per step
        ints = np.zeros((B, c + 10), np.int32)
        tok_blk = ints[:, 0:L]
        pos_blk = ints[:, L:2 * L]
        targets = ints[:, 2 * L:3 * L]
        draft = ints[:, 3 * L:c]
        bases = ints[:, c + 3:c + 5].view(np.uint32)
        temps = ints[:, c + 5].view(np.float32)
        temps[:] = 1.0
        pens = ints[:, c + 7:c + 10].view(np.float32)
        pens[:, 0] = 1.0  # repetition penalty (1 = off)
        need_mask = need_counts = False
        if self._pages is not None:
            tables = self._pages.tables()
            ps = self.serving.kv_page_size
            # targets default to the trash page 0
        else:
            targets[:] = B  # default: the trash row (cache batch B)
        for s in active:
            d = proposals.get(s.index, [])
            dl = len(d)
            p0 = s.prompt_len + len(s.generated) - 1
            prm = s.request.params
            row = ints[s.index]
            tok_blk[s.index, 0] = s.generated[-1]
            pos_blk[s.index, :] = p0  # clamp invalid rows' gathers
            for j, t in enumerate(d):
                tok_blk[s.index, j + 1] = t
                draft[s.index, j] = t
            pos_blk[s.index, :dl + 1] = p0 + np.arange(dl + 1)
            row[c] = dl  # dlen
            # counts: key-chain position, replay-offset like the L=1
            # sampler's column 0 (serving/migrate.py key_offset)
            row[c + 1] = prm.key_offset + len(s.generated)
            row[c + 2] = prm.top_k or 0  # topks
            bases[s.index] = self._base_keys[s.request.request_id]
            temps[s.index] = prm.temperature
            pens[s.index, 0] = prm.repetition_penalty
            pens[s.index, 1] = prm.presence_penalty
            pens[s.index, 2] = prm.frequency_penalty
            if self._slot_fsm(s) is not None:
                need_mask = True
            if _penalties_on(prm):
                need_counts = True
            if self._pages is not None:
                for j in range(dl + 1):
                    targets[s.index, j] = tables[
                        s.index, (int(pos_blk[s.index, j]) % M) // ps
                    ]
            else:
                targets[s.index, :dl + 1] = s.index
        dlen = ints[:, c]
        ints[0, c + 6] = int(faults.spec_reject_storm_at(iteration))
        # the verify pipeline's mask/histogram operands: per verify
        # row j, the FSM row for the state reached through drafts
        # 0..j-1 (walked host-side — table lookups, no device work)
        # and the PRE-BLOCK histogram (the kernel adds the in-block
        # draft cumsum itself). Inert cached constants when no active
        # slot engages the pipeline — the zero-recompile contract's
        # operand side.
        V = self.cfg.vocab_size
        allowed3, pcounts = self._inert_ops(("spec", B), (B, L))
        if need_mask:
            am = np.ones((B, L, V), bool)
            for s in active:
                fsm = self._slot_fsm(s)
                if fsm is None:
                    continue
                st = s.fsm_state
                am[s.index, 0] = fsm.allowed_row(st)
                for j, t in enumerate(proposals.get(s.index, [])):
                    st = fsm.advance(st, int(t))
                    am[s.index, j + 1] = fsm.allowed_row(st)
            allowed3 = jnp.asarray(am)
        if need_counts:
            cm = np.zeros((B, V), np.int32)
            for s in active:
                if _penalties_on(s.request.params):
                    cm[s.index] = self._slot_counts(s)
            pcounts = jnp.asarray(cm)
        # accept-variant pick: all-greedy steps run the threefry-free
        # specialization (bit-identical on greedy rows)
        spec_fn = self._spec_fn[
            any(s.request.params.temperature > 0 for s in active)
        ]
        decode_args = {
            "iteration": iteration, "active": len(active),
            "drafted": int(dlen.sum()),
        }
        if self._tracing:
            tids = [
                s.trace.trace_id for s in active if s.trace is not None
            ]
            if tids:
                decode_args["trace_ids"] = tids
        with self.tracer.span("decode", **decode_args):
            if self._pages is not None:
                out, self.cache = spec_fn(
                    self.params, jnp.asarray(ints), self.cache,
                    jnp.asarray(tables), allowed3, pcounts,
                )
            else:
                out, self.cache = spec_fn(
                    self.params, jnp.asarray(ints), self.cache,
                    allowed3, pcounts,
                )
        # one transfer for all three host-consumed outputs
        out = np.asarray(out)
        toks = out[:, :L]
        n_emit = out[:, L]
        ok = out[:, L + 1].astype(bool)
        bad = [s for s in active if not ok[s.index]]
        if bad:
            raise EngineCrashError(
                f"non-finite logits verifying slot(s) "
                f"{[s.index for s in bad]} (request(s) "
                f"{[s.request.request_id for s in bad]}): corrupt "
                "slot pool or numerically diverged params"
            )
        with self.tracer.span("emit", iteration=iteration):
            now = time.perf_counter()
            emitted = 0
            for s in active:
                dl = int(dlen[s.index])
                n = int(n_emit[s.index])
                if s.constraint is not None:
                    # a constraint can CLOSE mid-verify-window: every
                    # later row's mask is all-zero, so its "greedy
                    # correction" is argmax(-inf) garbage. Truncate at
                    # the first token produced by a zeroed row — the
                    # next step's sweep retires the slot typed, exactly
                    # like the non-spec path (which never consumes a
                    # zero mask because the sweep runs before decode).
                    st, keep = s.fsm_state, 0
                    for j in range(n):
                        if st < 0 or not s.constraint.masks[st].any():
                            break
                        st = s.constraint.advance(
                            st, int(toks[s.index, j])
                        )
                        keep += 1
                    n = keep
                p0 = s.prompt_len + len(s.generated) - 1
                if dl:
                    s.spec_proposed += dl
                    s.spec_accepted += n - 1
                    self.stats.inc("spec_proposed", dl)
                    self.stats.inc("spec_accepted", n - 1)
                # the drafter's validity cursor follows the ACCEPTED
                # prefix; rejected drafter-cache entries past it are
                # rewound (re-fed next round)
                self._drafter.commit(s.index, p0 + n)
                for j in range(n):
                    emitted += 1
                    self._emit(
                        s, int(toks[s.index, j]), now, finished,
                        lp=self._spec_lp_echo(s, out[s.index], j, L),
                        q=(self._spec_quality_echo(out[s.index], j, L)
                           if self._quality else None),
                    )
                    if s.state == FREE:
                        break  # EOS/stop/length retired the slot mid-block
            self.stats.inc("decode_tokens", emitted)

    def spec_stats(self) -> Optional[dict]:
        """Point-in-time speculative-decoding snapshot for /health
        (None when spec is off): mode, compiled draft rung, aggregate
        proposed/accepted/crash counters and the cumulative acceptance
        rate, plus the drafter's own locked counters."""
        if not self._spec_k:
            return None
        proposed = self.stats["spec_proposed"]
        accepted = self.stats["spec_accepted"]
        out = {
            "mode": self.serving.spec_mode,
            "verify": self.serving.spec_verify,
            "draft_len": self._spec_k,
            "proposed": proposed,
            "accepted": accepted,
            "acceptance_rate": (
                round(accepted / proposed, 4) if proposed else None
            ),
            "drafter_crashes": self.stats["spec_drafter_crashes"],
        }
        out["drafter"] = self._drafter.stats()
        return out

    def _update_gauges(self) -> None:
        """Refresh the point-in-time gauges (/metrics): slot occupancy,
        admission-queue depth, and the fraction of pooled KV positions
        holding live sequence state. Paged mode also mirrors the page
        pool's locked host counters into the registry."""
        occupied = self.scheduler.occupied()
        self._slot_gauge.set(occupied)
        self._queue_gauge.set(self.scheduler.queue_len())
        for cls, depth in self.scheduler.queue_depths().items():
            self._queue_class_gauge.set(depth, priority=cls)
        # structured-decoding mirror (BOTH cache layouts — keep it
        # ahead of the paged early-return below)
        self._constrained_gauge.set(len(self._constraints))
        cst = self._constraint_cache.stats()
        self._ccache_entries_gauge.set(cst["entries"])
        self._ccache_bytes_gauge.set(cst["bytes"])
        self._ccache_hits_counter.set(cst["hits_total"])
        self._ccache_misses_counter.set(cst["misses_total"])
        if self._spec_accept_gauge is not None:
            proposed = self.stats["spec_proposed"]
            self._spec_accept_gauge.set(
                self.stats["spec_accepted"] / proposed if proposed
                else 0.0
            )
        if self._quality_monitor is not None:
            # quality mirror (BOTH cache layouts — ahead of the paged
            # early-return below): the drift score is O(bins) host
            # math over the live sketches, nothing device-side
            self._q_drift_gauge.set(self._quality_monitor.drift())
            if self._q_constraint_total:
                self._q_validity_gauge.set(
                    1.0
                    - self._q_constraint_bad / self._q_constraint_total
                )
        if self._pages is not None:
            st = self._pages.stats()
            self._pages_free_gauge.set(st["free"])
            self._pages_cached_gauge.set(st["cached"])
            self._cow_forks_counter.set(st["cow_forks_total"])
            self._prefix_hits_counter.set(st["hits_total"])
            self._prefix_misses_counter.set(st["misses_total"])
            self._prefix_evictions_counter.set(st["evictions_total"])
            self._tier_prefix_hits_counter.set(st["tier_hits_total"])
            if self._tier is not None:
                ts = self._tier.stats()
                self._tier_bytes_gauge.set(ts["bytes"])
                self._tier_entries_gauge.set(ts["entries"])
                self._tier_stashes_gauge.set(ts["stashes"])
                self._tier_hits_counter.set(ts["hits_total"])
                self._tier_misses_counter.set(ts["misses_total"])
                self._tier_evictions_counter.set(ts["evictions_total"])
                self._tier_corrupt_counter.set(ts["corrupt_total"])
            held = sum(
                min(s.filled + len(s.generated), self.cfg.block_size)
                for s in self.scheduler.slots if s.state != FREE
            )
            self._kv_gauge.set(
                held / (st["total"] * self.serving.kv_page_size)
            )
            return
        held = sum(
            min(s.filled + len(s.generated), self.max_total)
            for s in self.scheduler.slots if s.state != FREE
        )
        self._kv_gauge.set(
            held / (self.serving.num_slots * self.max_total)
        )

    def page_stats(self) -> Optional[dict]:
        """Point-in-time page-pool snapshot for /health (None on the
        contiguous path): total/free/cached pages plus the monotonic
        prefix-cache counters (serving/pages.py:PagePool.stats)."""
        return None if self._pages is None else self._pages.stats()

    def tier_stats(self) -> Optional[dict]:
        """Point-in-time host-tier snapshot for /health (None when the
        tier is off): byte budget/usage, cached entries and pinned
        stashes, the tier's locked hit/miss/eviction/corrupt/rejected
        counters (serving/host_tier.py:HostTier.stats), plus the
        engine-side demote/promote/preempt/resume/fallback totals."""
        if self._tier is None:
            return None
        out = dict(self._tier.stats())
        out["demotions"] = self.stats["tier_demotions"]
        out["promotions"] = self.stats["tier_promotions"]
        out["fallbacks"] = self.stats["tier_fallbacks"]
        out["preemptions"] = self.stats["preemptions"]
        out["resumes"] = self.stats["resumes"]
        return out

    def queue_depths(self) -> dict:
        """Admission-queue depth by priority class (every class
        present, zero-filled) — the /health per-class view."""
        return self.scheduler.queue_depths()

    def constrain_stats(self) -> dict:
        """Point-in-time structured-decoding snapshot for /health:
        in-flight constrained requests plus the compile cache's locked
        counters (serving/constrain.py:ConstraintCache.stats)."""
        out = dict(self._constraint_cache.stats())
        out["active"] = len(self._constraints)
        return out

    # -- model-quality observability (obs/quality.py) ------------------

    def quality_stats(self) -> Optional[dict]:
        """Point-in-time quality snapshot for /health and serve_bench
        (None when quality telemetry is off): live sketch means, token
        counts, skipped ("no signal") observations, the PSI drift
        score, the constraint-validity rate, the cumulative spec
        acceptance when spec is on, and the per-layer lambda summary
        the gauges mirror."""
        if self._quality_monitor is None:
            return None
        out = self._quality_monitor.stats()
        out["constraint_validity_rate"] = (
            1.0 - self._q_constraint_bad / self._q_constraint_total
            if self._q_constraint_total else 1.0
        )
        proposed = self.stats["spec_proposed"]
        if proposed:
            out["spec_acceptance_rate"] = round(
                self.stats["spec_accepted"] / proposed, 4
            )
        out.update(self._lambda_summary)
        return out

    def quality_fingerprint(self,
                            meta: Optional[dict] = None) -> Optional[dict]:
        """The live sketches as a serializable reference fingerprint —
        ``--quality-record``'s payload (obs/quality.py:
        ``save_fingerprint`` writes it atomically at drain). None when
        telemetry is off."""
        if self._quality_monitor is None:
            return None
        return self._quality_monitor.fingerprint(meta=meta)

    def quality_row(self) -> Optional[dict]:
        """One ``{"record": "quality"}`` JSONL row (the serving twin
        of the trainer's introspection records), carrying the
        ``lambda_l<k>`` keys ``tools/lambda_report.py --serving``
        renders beside training rows. None when telemetry is off."""
        if self._quality_monitor is None:
            return None
        return build_quality_row(
            self._quality_monitor, self.stats["iterations"],
            lambdas=self._lambda_summary,
        )

    def _refresh_lambda_gauges(self) -> None:
        """Mirror the SERVING params' per-layer effective lambdas into
        ``serving_lambda_mean{layer=}`` — obs/introspect.py walks the
        same ops/lambdas.py path the trainer logs, so ROADMAP item 6's
        diff-vs-control comparison reads straight off a live fleet.
        Called at build and after any params rebind (the quality_drift
        fault), never per step: the summary fetches device scalars."""
        if self._lambda_gauge is None:
            return
        from differential_transformer_replication_tpu.obs.introspect import (
            serving_lambda_summary,
        )

        self._lambda_summary = serving_lambda_summary(
            self.params, self.cfg
        )
        for key, val in self._lambda_summary.items():
            if "_t" in key:
                continue  # per-term ndiff detail rides quality_row only
            self._lambda_gauge.set(val, layer=key[len("lambda_l"):])

    def _apply_quality_drift(self) -> None:
        """Fault-injection helper (``quality_drift@N``): perturb the
        live params so generated DISTRIBUTIONS shift while every logit
        stays finite — requests keep succeeding and latency stays
        flat, so only the drift detector can catch it (the canary
        chaos drill's point). Every family gets lm_head scaled by
        0.25: the sampled distribution flattens (entropy up, margin
        down) while the greedy argmax is bit-unchanged — on control,
        greedy traffic's tokens are untouched and only the
        fingerprint convicts. diff/ndiff additionally get +2.0 on BOTH
        lambda_q[0] and lambda_k[0] of layer 1 — λ rides exp(lq·lk)
        and the reference initializes those vectors to zero, so one
        side alone is a no-op; shifting both moves term 0's
        exponential by ~exp(4) (bounded, finite), which the
        ``serving_lambda_mean`` gauges surface as the fault's visible
        signature. Params are never donated by the jitted steps, so
        rebinding a shallow-copied tree is safe; the lambda gauges
        refresh to show the perturbed values."""
        params = dict(self.params)
        if self.cfg.model in ("diff", "ndiff"):
            blocks = list(params["blocks"])
            blk = dict(blocks[0])
            attn = dict(blk["attn"])
            for name in ("lambda_q", "lambda_k"):
                vec = attn[name]
                attn[name] = vec.at[0].add(2.0)
            blk["attn"] = attn
            blocks[0] = blk
            params["blocks"] = blocks
        params["lm_head"] = jax.tree_util.tree_map(
            lambda a: a * 0.25, params["lm_head"]
        )
        self.params = params
        self._refresh_lambda_gauges()

    def take_finished(self) -> List[RequestOutput]:
        """Outputs accumulated by a :meth:`step` that raised partway
        through. Those requests were already retired (slot freed / shed
        from the queue), so after a crash they are invisible to both
        :meth:`reset_after_crash`'s lost-list and the preserved queue —
        the supervisor (serving/server.py) must drain this buffer and
        deliver them, or their callers would hang forever."""
        out, self._finished_prior = self._finished_prior, []
        return out

    def run(self) -> List[RequestOutput]:
        """Drain the queue; returns every output, in completion order."""
        outs: List[RequestOutput] = []
        while self.scheduler.has_work():
            outs.extend(self.step())
        return outs

    def generate(self, prompts: Sequence[Sequence[int]],
                 params: Optional[Sequence[SamplingParams]] = None,
                 **kw) -> List[RequestOutput]:
        """Submit-all + drain convenience; outputs in submission order.
        ``params`` gives per-request SamplingParams; otherwise ``kw``
        build one shared SamplingParams."""
        shared = SamplingParams(**kw) if params is None else None
        ids = []
        try:
            for i, p in enumerate(prompts):
                ids.append(self.submit(p, params=shared if shared else params[i]))
        except Exception:
            # mid-batch rejection (max_queue_len): the prompts already
            # queued would otherwise sit in the scheduler and burn a
            # later run()'s decode iterations for nobody
            for rid in ids:
                self.cancel(rid)
            raise
        by_id = {o.request_id: o for o in self.run()}
        return [by_id[i] for i in ids]

    def close(self) -> None:
        """Release host-side resources: drain the device-profile
        sampler (its queued parse must land before the process exits).
        Idempotent; called by EngineRunner's shutdown paths."""
        if self._device_prof is not None:
            self._device_prof.close()

    def compile_stats(self) -> dict:
        """Compile-cache sizes of the engine's jitted closures. Pinned by
        tests/test_serving.py: decode must stay at 1 entry no matter how
        requests come and go. NOTE the closures are shared across engines
        with identical (cfg, max_seq_len) — counts are per-config, not
        per-instance."""
        out = {
            "prefill": self._prefill_fn._cache_size(),
            "decode": self._decode_fn._cache_size(),
            "sample": self._sample_fn._cache_size(),
        }
        if self._copy_fn is not None:
            out["page_copy"] = self._copy_fn._cache_size()
        if self._extract_fn is not None:
            # the host tier's transfer closures: scalar page indices
            # ride as runtime arrays, so demote/promote/preempt/resume
            # churn pins each at 1 entry (tests/test_tiering.py)
            out["page_extract"] = self._extract_fn._cache_size()
            out["page_inject"] = self._inject_fn._cache_size()
        if self._spec_fn is not None:
            # the k rung of the verify ladder (both accept variants);
            # "decode" above is the k=0 rung — together they are THE
            # fixed-ladder compile budget the spec tests pin
            out["spec_decode"] = sum(
                fn._cache_size() for fn in self._spec_fn.values()
            )
        return out

    def _on_retire(self, slot: Slot) -> None:
        """Scheduler retirement hook (every retire path: finish,
        deadline, cancel): return the slot's KV pages (paged) and drop
        its drafter-side state (spec)."""
        if self._pages is not None:
            self._release_slot_pages(slot)
        if self._drafter is not None:
            self._drafter.release(slot.index)

    # -- paged admission / release (serving/pages.py) ------------------

    def _admit_paged(self, slot: Slot, entry, iteration: int,
                     finished: List[RequestOutput]) -> Optional[int]:
        """Scheduler admission gate: plan the selected request against
        the radix cache + page pool (and, when tiered, the host tier).
        Returns the cached/restored prefix length to skip (>= 0), None
        to keep it queued (transient page shortage — the scheduler may
        preempt a lower class on this verdict and retry), or -1 after
        shedding it with the typed :class:`PagePoolExhaustedError`
        output."""
        request, prompt, t_submit, _deadline, trace = entry
        if request.request_id in self._resume:
            verdict = self._try_resume(slot, entry, iteration)
            if verdict == "wait":
                return None
            if verdict == "ok":
                # the full KV image (prompt AND generated) was
                # re-injected: nothing to prefill
                return int(prompt.shape[0])
            # "restart": the stash was unusable — fall through to a
            # fresh admission; fold_in(key, t) token keys make the
            # recomputed output bit-identical to the uninterrupted run
        try:
            adm = self._pages.plan_admission(
                slot.index, [int(t) for t in prompt],
                request.params.max_new_tokens,
            )
        except PagePoolExhaustedError:
            self._drain_demotions(iteration)
            finished.append(
                self._shed_page_exhausted(request, prompt, t_submit,
                                          trace)
            )
            return -1
        # demotion plans from this planning call's evictions MUST be
        # captured before any copy/promote/prefill could overwrite the
        # freed physical pages (serving/pages.py:take_demotions)
        self._drain_demotions(iteration)
        if adm is None:
            return None
        cached = adm.cached_len
        if adm.promotes:
            cached = self._apply_promotes(adm, iteration)
        for src, dst in adm.copies:
            # COW fork: the shared page's prefix K/V lands on a page
            # this slot privately owns; applied BEFORE any further
            # pool call (the pool's eviction invariant)
            self.cache = self._copy_fn(
                self.cache, np.int32(src), np.int32(dst)
            )
        return cached

    # -- host tier: demote / promote / preempt / resume ----------------
    # (serving/host_tier.py; all single-engine-thread, pool lock ->
    # tier lock order per GL601)

    def _extract_page(self, page: int) -> list:
        """One physical page's device bytes as OWNED, writable host
        numpy (per-layer leaf dicts) — the capture side of demotion
        and preemption stashing. ``np.array`` (not ``asarray``): the
        tier checksums the buffer and the swap-corrupt fault flips a
        byte in place, so the copy must not alias device memory."""
        out = self._extract_fn(self.cache, np.int32(page))
        return [
            {key: np.array(leaf) for key, leaf in layer.items()}
            for layer in out
        ]

    def _inject_page(self, page: int, payload) -> bool:
        """Write one host page image into physical page ``page`` (the
        promote/swap-in transfer), retried with a short backoff —
        a transient device_put failure degrades to recompute at the
        caller, never a wedge."""
        for attempt in range(3):
            try:
                self.cache = self._inject_fn(
                    self.cache, np.int32(page), payload
                )
                return True
            except Exception:
                if attempt == 2:
                    return False
                time.sleep(0.005 * (attempt + 1))
        return False

    def _drain_demotions(self, iteration: int) -> None:
        """Capture the pool's pending demotion plans into the host
        tier. Runs immediately after EVERY pool planning call (success
        or not): the freed pages' device bytes are still the evicted
        prefix until a later planning call hands them back out. A
        failed capture (the ``page_demote_fail`` fault) just skips the
        tier — the prefix degrades to recompute, typed and counted."""
        if self._tier is None:
            return
        plans = self._pages.take_demotions()
        if not plans:
            return
        if faults.page_demote_fail_at(iteration):
            self.stats.inc("tier_fallbacks", len(plans))
            return
        for prefix, page in plans:
            if self._tier.put(prefix, self._extract_page(page)):
                self.stats.inc("tier_demotions")

    def _apply_promotes(self, adm, iteration: int) -> int:
        """Stage an admission's host-tier pages back onto the device
        (a copy, never a recompute). Pages apply in prompt order; the
        first failed verify/inject truncates the restored prefix there
        — the remainder simply prefills. The ``page_promote_hang``
        fault stalls (DTX_TIER_HANG_S) then fails every promote."""
        ps = self.serving.kv_page_size
        ok_pages = 0
        if not faults.page_promote_hang_at(iteration):
            for dst, ent in adm.promotes:
                if not ent.verify():
                    self._tier.note_corrupt()
                    break
                if not self._inject_page(int(dst), ent.payload):
                    break
                ok_pages += 1
        if ok_pages:
            self.stats.inc("tier_promotions", ok_pages)
        if ok_pages < len(adm.promotes):
            self.stats.inc(
                "tier_fallbacks", len(adm.promotes) - ok_pages
            )
        return adm.device_cached + ok_pages * ps

    def _preempt_slot(self, slot: Slot) -> None:
        """Scheduler preemption hook (plan()'s blocked-admission path):
        stash an ACTIVE lower-priority slot's live KV pages and host
        decode state to the tier, free its pages, and REQUEUE it with
        its ORIGINAL submit_time so anti-starvation aging keeps
        accruing. The later swap-in (:meth:`_try_resume`) is bit-exact
        — no recompute, no recompile."""
        rid = slot.request.request_id
        ps = self.serving.kv_page_size
        # pages actually written so far: after emitting g tokens the
        # device KV covers positions 0..P+g-2 (the last token's KV is
        # written by its NEXT step); ceil((P+g)/ps) over-covers that
        # and never exceeds the slot's allocation
        pos = slot.prompt_len + len(slot.generated)
        n_live = min(-(-pos // ps), self._pages.pages_per_slot)
        row = self._pages.table_row(slot.index)
        payloads = [
            self._extract_page(int(row[j])) for j in range(n_live)
        ]
        self._tier.stash(rid, payloads)
        self._resume[rid] = {
            "n_live": n_live,
            "generated": list(slot.generated),
            "token_times": list(slot.token_times),
            "first_token_time": slot.first_token_time,
            "filled": slot.filled,
            "cached_len": slot.cached_len,
            "spec_proposed": slot.spec_proposed,
            "spec_accepted": slot.spec_accepted,
            "prompt_ids": slot.prompt_ids,
            "penalty_counts": slot.penalty_counts,
            "token_logprobs": slot.token_logprobs,
            "top_logprobs": slot.top_logprobs,
            "fsm_state": slot.fsm_state,
        }
        self.scheduler.queue.append(
            (slot.request, slot.prompt, slot.submit_time,
             slot.deadline, slot.trace)
        )
        self._pages.release(slot.index, [], False)
        if self._drafter is not None:
            self._drafter.release(slot.index)
        self.stats.inc("preemptions")
        # reset directly, NOT scheduler.retire: the retire hook would
        # release the slot's pages a second time
        slot.reset()

    def _try_resume(self, slot: Slot, entry, iteration: int) -> str:
        """Swap a preempted request back in: reserve private pages for
        its FULL KV image and inject the stash, checksum-verified.
        Returns "wait" (pool cannot free enough yet — the scheduler
        may preempt for it), "ok" (resumed bit-exact; step() restores
        the host state after plan() commits), or "restart" (stash
        unusable — degrade to a bit-exact full recompute, typed and
        counted)."""
        request, prompt, _t_submit, _deadline, _trace = entry
        rid = request.request_id
        snap = self._resume[rid]
        pages = self._pages.plan_resume(
            slot.index,
            self._pages.pages_needed(
                int(prompt.shape[0]), request.params.max_new_tokens
            ),
        )
        self._drain_demotions(iteration)
        if pages is None:
            return "wait"
        # a snapshot carrying its own page images came over the WIRE
        # (a migrated slot state, serving/migrate.py:import_state) —
        # inject from it instead of the host-tier stash; everything
        # downstream (verify, inject, restore) is shared machinery
        migrated = "pages" in snap
        ents = snap["pages"] if migrated else self._tier.unstash(rid)
        ok = ents is not None
        if ok and faults.page_swap_corrupt_at(iteration):
            # flip one byte of the first payload leaf in place: the
            # CRC verify below must catch it and degrade to restart
            layer0 = ents[0].payload[0]
            leaf = layer0[next(iter(layer0))]
            leaf.reshape(-1).view(np.uint8)[0] ^= 0xFF
        if ok:
            for pg, ent in zip(pages, ents):
                if not ent.verify():
                    if self._tier is not None:
                        self._tier.note_corrupt()
                    ok = False
                    break
                if not self._inject_page(int(pg), ent.payload):
                    ok = False
                    break
        if not ok:
            self._pages.release(slot.index, [], False)
            self._resume.pop(rid, None)
            if migrated:
                # fresh admission below recomputes the whole image;
                # fold_in(key, t) keys make the regenerated stream
                # bit-identical, so the import degrades, never lies
                self.stats.inc("migrate_failed")
            else:
                self._tier.drop_stash(rid)
                self.stats.inc("tier_fallbacks")
            # the bit-exact recompute re-emits every token: reset the
            # per-request quality accumulator so means are not doubled
            self._q_acc.pop(rid, None)
            return "restart"
        self._resumed.append((slot, snap))
        self.stats.inc("resumes")
        return "ok"

    def _drop_resume(self, request_id: int) -> None:
        """Forget a preempted request's swap-in state on every path
        that forgets its key chain (cancel, expire, shed, crash loss)
        — a leaked stash would pin host-tier bytes forever."""
        self._resume.pop(request_id, None)
        if self._tier is not None:
            self._tier.drop_stash(request_id)

    # -- live migration (serving/migrate.py) ---------------------------
    # Engine-thread only, like every other device-touching method: the
    # runner (serving/server.py) executes these between steps.

    def _slot_for(self, request_id: int) -> Optional[Slot]:
        return next(
            (s for s in self.scheduler.slots
             if s.state != FREE and s.request is not None
             and s.request.request_id == request_id),
            None,
        )

    def export_slot_state(self, request_id: int,
                          dedup_pages: int = 0) -> bytes:
        """Capture one ACTIVE slot's full decode state as a wire image
        WITHOUT disturbing it — the slot keeps decoding until the
        destination ACKs and :meth:`release_migrated` retires it, so a
        failed transfer costs nothing. ``dedup_pages`` is the
        destination's radix-probe answer (PagePool.probe_prefix):
        that many leading full prompt pages ship as holes the importer
        copies device-locally. Raises the typed
        :class:`MigrateExportError` when there is nothing exportable
        (contiguous layout, request queued/prefilling/finished)."""
        if self._pages is None or self._extract_fn is None:
            raise MigrateExportError(
                "live migration needs the paged KV layout "
                "(ServingConfig.kv_page_size > 0) — fall back to replay"
            )
        slot = self._slot_for(request_id)
        if slot is None or slot.state != ACTIVE or not slot.generated:
            raise MigrateExportError(
                f"request {request_id} holds no ACTIVE slot (queued, "
                "prefilling, or already finished) — nothing to "
                "migrate; replay or plain retry covers it",
                code="migrate_not_active",
            )
        faults.stall("migrate_hang")
        ps = self.serving.kv_page_size
        p = slot.request.params
        # live pages: same arithmetic as _preempt_slot — after g
        # emitted tokens the device KV covers positions 0..P+g-2
        pos = slot.prompt_len + len(slot.generated)
        n_live = min(-(-pos // ps), self._pages.pages_per_slot)
        # dedup can only cover FULL pages of the PROMPT (generated
        # tokens never live in a radix tree), and the radix match is
        # capped at prompt_len - 1
        dedup = max(0, min(
            int(dedup_pages), n_live,
            (slot.prompt_len - 1) // ps if slot.prompt_len else 0,
        ))
        row = self._pages.table_row(slot.index)
        payloads: List[Optional[list]] = [
            None if j < dedup else self._extract_page(int(row[j]))
            for j in range(n_live)
        ]
        now = time.perf_counter()
        meta = {
            "prompt": [int(t) for t in slot.prompt],
            "params": params_to_dict(p),
            "generated": list(slot.generated),
            "n_live": n_live,
            "dedup_pages": dedup,
            "page_size": ps,
            "model": self.cfg.model,
            "block_size": self.cfg.block_size,
            "filled": slot.filled,
            "cached_len": slot.cached_len,
            "spec_proposed": slot.spec_proposed,
            "spec_accepted": slot.spec_accepted,
            "fsm_state": slot.fsm_state,
            "token_logprobs": slot.token_logprobs,
            "top_logprobs": slot.top_logprobs,
            "deadline_left_s": (
                max(0.0, slot.deadline - now) if slot.deadline else 0.0
            ),
        }
        blob = encode_slot_state(meta, payloads)
        if payloads and faults.consume("migrate_corrupt"):
            # chaos drill: flip one byte AFTER the per-page CRCs were
            # stamped — the import side's decode must convict the
            # transfer (MigratePayloadError), and the drain path falls
            # back to replay; garbage KV is never attended
            torn = bytearray(blob)
            torn[-1] ^= 0xFF
            blob = bytes(torn)
        self.stats.inc("migrate_exports")
        self.stats.inc("migrate_pages_shipped", n_live - dedup)
        self.stats.inc("migrate_pages_deduped", dedup)
        self.stats.inc("migrate_bytes", len(blob))
        return blob

    def release_migrated(self, request_id: int) -> bool:
        """Retire a slot whose decode state now lives on the
        destination replica (the import was ACKed). Same engine thread
        as the export, so the slot cannot have stepped in between.
        Returns False when the request is unknown/finished — the local
        output wins and the caller abandons the migration."""
        slot = self._slot_for(request_id)
        if slot is None:
            return False
        self._base_keys.pop(request_id, None)
        self._drop_constraint(request_id)
        self._drop_resume(request_id)
        self._q_acc.pop(request_id, None)
        self._finished_counter.inc(reason="migrated")
        if self._tracing:
            self.tracer.instant(
                "finish", rid=request_id, reason="migrated",
                **(instant_args(slot.trace)
                   if slot.trace is not None else {}),
            )
        # standard retire path: pages dereferenced (prompt prefix
        # donated to the radix cache when trustworthy) + drafter state
        # dropped — the SOURCE keeps serving the prefix to new traffic
        self.scheduler.retire(slot)
        return True

    def import_state(self, blob: bytes) -> int:
        """Re-admit a migrated slot state: decode + checksum-verify the
        wire image (serving/migrate.py — a flipped byte is convicted
        HERE, before anything reaches the device), resolve dedup holes
        from the local radix tree, then ride the SAME zero-recompile
        swap-in machinery as host-tier resume: submit() mints a fresh
        request id (key chain, constraint compile, deadline from the
        shipped remainder) and the registered ``self._resume`` snapshot
        makes the paged admission gate inject the pages bit-exact
        (:meth:`_try_resume`). Returns the minted request id. Raises
        :class:`MigratePayloadError` (corrupt/torn) or
        :class:`MigrateExportError` (geometry mismatch, dedup miss,
        contiguous layout) — both typed, both leave the engine clean."""
        if self._pages is None or self._inject_fn is None:
            raise MigrateExportError(
                "live migration needs the paged KV layout "
                "(ServingConfig.kv_page_size > 0)"
            )
        meta, payloads = decode_slot_state(blob)
        if (meta.get("page_size") != self.serving.kv_page_size
                or meta.get("model") != self.cfg.model
                or meta.get("block_size") != self.cfg.block_size):
            raise MigrateExportError(
                f"geometry mismatch: wire (model={meta.get('model')}, "
                f"block={meta.get('block_size')}, "
                f"page={meta.get('page_size')}) vs engine "
                f"(model={self.cfg.model}, block={self.cfg.block_size},"
                f" page={self.serving.kv_page_size})",
                code="migrate_geometry",
            )
        prompt = [int(t) for t in meta["prompt"]]
        dedup = int(meta.get("dedup_pages", 0))
        if dedup:
            # resolve the holes from the local radix tree NOW (same
            # engine thread, no planning call until submit below, so
            # the chain cannot be evicted under us); a miss — evicted
            # since the probe — fails typed and the source keeps the
            # request untouched
            chain = self._pages.chain_pages(prompt, dedup)
            if chain is None:
                self.stats.inc("migrate_failed")
                raise MigrateExportError(
                    f"dedup chain ({dedup} pages) no longer cached — "
                    "evicted between probe and import; source retries "
                    "without dedup or falls back to replay",
                    code="migrate_dedup_miss",
                )
            for j, pg in enumerate(chain):
                payloads[j] = self._extract_page(int(pg))
        params = params_from_dict(meta["params"])
        left = float(meta.get("deadline_left_s") or 0.0)
        rid = self.submit(
            prompt, params=params,
            deadline=(time.perf_counter() + left) if left else None,
        )
        self._resume[rid] = {
            "n_live": int(meta["n_live"]),
            "generated": [int(t) for t in meta["generated"]],
            # host timestamps do not survive the process hop: token
            # times restart on the destination clock (ITL histograms
            # skip the splice point; finish_time stays monotonic)
            "token_times": [],
            "first_token_time": time.perf_counter(),
            "filled": int(meta["filled"]),
            "cached_len": int(meta["cached_len"]),
            "spec_proposed": int(meta.get("spec_proposed", 0)),
            "spec_accepted": int(meta.get("spec_accepted", 0)),
            "prompt_ids": None,
            "penalty_counts": None,  # _slot_counts rebuilds lazily
            "token_logprobs": meta.get("token_logprobs"),
            "top_logprobs": (
                [[(int(i), float(v)) for i, v in alts]
                 for alts in meta["top_logprobs"]]
                if meta.get("top_logprobs") is not None else None
            ),
            "fsm_state": int(meta.get("fsm_state", 0)),
            # wire-borne page images: _try_resume injects these instead
            # of a host-tier stash (checksums re-verified at injection)
            "pages": [TierEntry(p) for p in payloads],
        }
        self.stats.inc("migrate_imports")
        return rid

    def progress_snapshot(self) -> List[dict]:
        """Per-in-flight-request emitted-token progress — the
        ``GET /inflight`` body the router harvests into its replay
        journal (serving/migrate.py:ReplayJournal). Engine thread
        (published by the runner between steps); the journal only
        needs a PREFIX of the truly-emitted tokens, so lagging a step
        is correct by construction."""
        out = []
        for s in self.scheduler.slots:
            if s.state == FREE or s.request is None:
                continue
            out.append({
                "request_id": s.request.request_id,
                "prompt_len": s.prompt_len,
                "tokens": list(s.generated),
            })
        for req, prompt, _t, _dl, _tr in list(self.scheduler.queue):
            out.append({
                "request_id": req.request_id,
                "prompt_len": int(prompt.shape[0]),
                "tokens": [],
            })
        return out

    def _release_slot_pages(self, slot: Slot) -> None:
        """Scheduler retirement hook (every retire path: finish,
        deadline, cancel): dereference shared pages and donate the
        prompt's pages to the radix cache when they are trustworthy —
        prompt fully prefilled and the ring never rolled over them."""
        prompt = [] if slot.prompt is None else [int(t) for t in slot.prompt]
        cacheable = (
            slot.prompt_len > 0
            and slot.filled == slot.prompt_len
            and slot.prompt_len + len(slot.generated)
            <= self.cfg.block_size
        )
        self._pages.release(slot.index, prompt, cacheable)

    def _shed_page_exhausted(self, request, prompt, submit_time: float,
                             trace=None) -> RequestOutput:
        """A request the page pool refused (never fits, or the
        ``page_exhaust`` fault): shed at admission with a typed output
        the server maps to the 503 shed path — it never touches the
        device."""
        self._base_keys.pop(request.request_id, None)
        self._drop_constraint(request.request_id)
        self._drop_resume(request.request_id)
        self._q_acc.pop(request.request_id, None)
        self.stats.inc("page_shed")
        self._finished_counter.inc(reason="page_exhausted")
        if self._tracing:
            self.tracer.instant(
                "finish", rid=request.request_id,
                reason="page_exhausted",
                **(instant_args(trace) if trace is not None else {}),
            )
        # Retry-After from the pool's OBSERVED drain rate: seconds
        # until enough pages free for THIS request at the recent
        # eviction/release throughput, instead of a static guess —
        # serving/retry.py honors it as the client backoff floor and
        # the server echoes it in the 503's Retry-After header
        retry_after = self._pages.estimated_drain_s(
            self._pages.pages_needed(
                len(prompt), request.params.max_new_tokens
            )
        )
        return RequestOutput(
            request_id=request.request_id,
            prompt=[int(t) for t in prompt],
            tokens=[],
            finish_reason="page_exhausted",
            submit_time=submit_time,
            first_token_time=0.0,
            finish_time=time.perf_counter(),
            token_times=[],
            trace_id=trace.trace_id if trace is not None else None,
            retry_after=retry_after,
        )

    def _corrupt_cached_prefix(self) -> None:
        """Fault-injection helper (``prefix_corrupt@N``): NaN-poison
        one radix-cached page, preferring one currently shared with an
        occupied slot so the very next decode trips the finite-logits
        guard — the supervised restart then rebuilds the pool and the
        poisoned prefix is evicted wholesale instead of ever serving
        garbage tokens (serving/pages.py:PagePool.reset)."""
        cached = set(self._pages.cached_pages())
        if not cached:
            return
        tables = self._pages.tables()
        target = None
        for s in self.scheduler.slots:
            if s.state == FREE:
                continue
            for pg in tables[s.index]:
                if int(pg) in cached:
                    target = int(pg)
                    break
            if target is not None:
                break
        if target is None:
            target = next(iter(cached))
        self.cache = self._poison_pages([target])

    def _poison_pages(self, pages: List[int]) -> list:
        """NaN-poison the given physical pages across every layer/leaf
        (int8 values zero while their fp32 scales go NaN, so every
        dequantized read is NaN — same trick as _corrupt_one_slot)."""
        idx = np.asarray(pages, np.int32)

        def _poison(key, arr):
            ix = (
                (slice(None), idx) if KV_CACHE_BATCH_AXIS[key] else idx
            )
            if jnp.issubdtype(arr.dtype, jnp.floating):
                return arr.at[ix].set(jnp.nan)
            return arr.at[ix].set(0)

        return [
            {key: _poison(key, c[key]) for key in c} for c in self.cache
        ]

    # -- internals ----------------------------------------------------

    def _slot_fsm(self, s: Slot):
        """The slot's compiled token FSM, attached lazily (admission
        happens inside the scheduler, which knows nothing of
        constraints; the engine-side map is keyed by request_id). None
        for unconstrained requests."""
        if s.constraint is None:
            ent = self._constraints.get(s.request.request_id)
            if ent is None:
                return None
            s.constraint = ent[1]
            s.fsm_state = ent[1].start
            ko = s.request.params.key_offset
            if ko:
                # replayed continuation (serving/migrate.py): the dead
                # attempt's FSM already consumed the tokens now riding
                # the prompt tail — walk the fresh cursor over them so
                # masks continue from the same state
                P = s.prompt_len
                st = s.fsm_state
                for t in s.prompt[max(0, P - ko):P]:
                    if st < 0:
                        break
                    st = ent[1].advance(st, int(t))
                s.fsm_state = st
        return s.constraint

    def _slot_counts(self, s: Slot) -> np.ndarray:
        """The slot's generated-token histogram — built once at the
        first penalized sample, then incremented per emitted token
        (_emit); rebuilding the (V,) array per iteration would be the
        exact host cost class the packed operands exist to avoid."""
        if s.penalty_counts is None:
            h = np.zeros((self.cfg.vocab_size,), np.int32)
            ko = s.request.params.key_offset
            if ko:
                # replayed continuation: the dead attempt's emitted
                # tokens (now the prompt tail) were penalized then, so
                # they seed the histogram here — same distribution as
                # the uninterrupted run
                P = s.prompt_len
                for t in s.prompt[max(0, P - ko):P]:
                    h[int(t)] += 1
            for t in s.generated:
                h[t] += 1
            s.penalty_counts = h
        return s.penalty_counts

    def _inert_ops(self, key, shape):
        """Cached all-ones mask + zero-histogram DEVICE constants for
        a pipeline call with no constrained/penalized active rows:
        the common case pays no per-step (B, V) host build or
        transfer, and the pipeline's ``where`` passes raw logits
        through bit-identically."""
        ops = self._inert.get(key)
        if ops is None:
            V = self.cfg.vocab_size
            ops = (
                jnp.ones(shape + (V,), bool),
                jnp.zeros((shape[0], V), jnp.int32),
            )
            self._inert[key] = ops
        return ops

    def _sample_operands(self, rows, B):
        """Packed (B, 8) int32 sampler operand plus the pipeline's
        allowed/counts arrays for a (row index, slot) assignment (see
        _build_step_fns._sample for the column layout; quality
        telemetry widens it by one previous-token column). Rows not
        named keep inert defaults (temp 1, penalties off, mask
        all-ones, no previous token)."""
        ints = np.zeros((B, 9 if self._quality else 8), np.int32)
        f = ints[:, 4:8].view(np.float32)
        f[:, 0] = 1.0  # temperature
        f[:, 1] = 1.0  # repetition penalty (1 = off)
        if self._quality:
            ints[:, 8] = -1  # no previous token (repeat flag stays 0)
        need_mask = need_counts = False
        for i, s in rows:
            p = s.request.params
            # key-chain position: a replayed continuation (key_offset >
            # 0, serving/migrate.py) samples token t with the key the
            # DEAD attempt would have used at global position
            # key_offset + t — bit-identical streams across failover
            ints[i, 0] = p.key_offset + len(s.generated)
            ints[i, 1] = p.top_k or 0
            ints[i, 2:4].view(np.uint32)[:] = (
                self._base_keys[s.request.request_id]
            )
            f[i, 0] = p.temperature
            f[i, 1] = p.repetition_penalty
            f[i, 2] = p.presence_penalty
            f[i, 3] = p.frequency_penalty
            if self._quality:
                # the token the sampled one would repeat: the last
                # emitted, or (first sample, at prefill completion)
                # the last prompt token
                if s.generated:
                    ints[i, 8] = s.generated[-1]
                elif s.prompt_len:
                    ints[i, 8] = int(s.prompt[s.prompt_len - 1])
            if self._slot_fsm(s) is not None:
                need_mask = True
            if _penalties_on(p):
                need_counts = True
        allowed, counts = self._inert_ops(B, (B,))
        if need_mask:
            am = np.ones((B, self.cfg.vocab_size), bool)
            for i, s in rows:
                fsm = self._slot_fsm(s)
                if fsm is not None:
                    am[i] = fsm.allowed_row(s.fsm_state)
            allowed = jnp.asarray(am)
        if need_counts:
            cm = np.zeros((B, self.cfg.vocab_size), np.int32)
            for i, s in rows:
                if _penalties_on(s.request.params):
                    cm[i] = self._slot_counts(s)
            counts = jnp.asarray(cm)
        return ints, allowed, counts

    def _sample_rows(self, slots: List[Slot], logits):
        """Sample one token for each given slot from (n, V) logits
        through the logit pipeline; returns (tokens, finite-ok,
        packed echo rows) — the packed layout is
        _build_step_fns._sample's output contract."""
        ints, allowed, counts = self._sample_operands(
            list(enumerate(slots)), len(slots)
        )
        out = np.asarray(self._sample_fn(
            jnp.asarray(ints), logits, allowed, counts
        ))
        return out[:, 0], out[:, 1].astype(bool), out

    def _sample_all_slots(self, logits):
        """Full-pool variant with inert defaults on non-active rows, so
        the decode-path sampler always sees the same (B, V) shape.
        Returns (tokens, finite-ok, packed); only ACTIVE rows mean
        anything (inactive rows compute garbage by design)."""
        ints, allowed, counts = self._sample_operands(
            [(s.index, s) for s in self.scheduler.active_slots()],
            self._rows,
        )
        out = np.asarray(self._sample_fn(
            jnp.asarray(ints), logits, allowed, counts
        ))
        return out[:, 0], out[:, 1].astype(bool), out

    def _lp_echo(self, s: Slot, row: np.ndarray):
        """Decode one sampler echo row into the (chosen logprob,
        [(token id, logprob)] top list) pair _emit accumulates — None
        when the request asked for none (``params.logprobs == 0``).
        Per-request widths <= the compiled lp_k are host-side
        truncation, never a new trace."""
        n = s.request.params.logprobs
        if not n:
            return None
        K = self._lp_k
        chosen = float(row[2:3].view(np.float32)[0])
        k = min(n, K)
        ids = row[3:3 + k]
        lps = row[3 + K:3 + K + k].view(np.float32)
        return chosen, [
            (int(i), float(v)) for i, v in zip(ids, lps)
        ]

    def _spec_lp_echo(self, s: Slot, row: np.ndarray, j: int, L: int):
        """Per-row logprob echo from the spec verify step's packed
        output (see _build_spec_step_fns._pack_out): verify row j's
        chosen-token logprob + top list for the same request surface
        as :meth:`_lp_echo`."""
        n = s.request.params.logprobs
        if not n:
            return None
        K = self._lp_k
        base = L + 2
        chosen = float(row[base + j:base + j + 1].view(np.float32)[0])
        k = min(n, K)
        o = base + L + j * K
        ids = row[o:o + k]
        lps = row[o + L * K:o + L * K + k].view(np.float32)
        return chosen, [
            (int(i), float(v)) for i, v in zip(ids, lps)
        ]

    def _quality_echo(self, row: np.ndarray):
        """The L=1 sampler's appended quality tail as host floats —
        (entropy, margin, repeat flag), the bitcast twin of
        :meth:`_lp_echo` (see _build_step_fns._sample's layout)."""
        base = 3 + 2 * self._lp_k
        q = row[base:base + 3].view(np.float32)
        return float(q[0]), float(q[1]), float(q[2])

    def _spec_quality_echo(self, row: np.ndarray, j: int, L: int):
        """Verify row j's quality tail from the spec step's packed
        output: ent | margin | rep blocks of L columns each, appended
        after the logprob echo (_build_spec_step_fns._pack_out)."""
        base = 2 + 2 * L + 2 * L * self._lp_k
        ent = row[base + j:base + j + 1].view(np.float32)[0]
        margin = row[base + L + j:base + L + j + 1].view(np.float32)[0]
        rep = row[base + 2 * L + j:base + 2 * L + j + 1].view(
            np.float32
        )[0]
        return float(ent), float(margin), float(rep)

    def _q_observe(self, rid: int, q) -> None:
        """Fold one emitted token's quality tail into the histograms,
        the drift monitor's sketches, and the per-request accumulator
        (keyed by request id, so preempt/resume carries it for free).
        The ``quality_nan`` fault poisons the values HERE: non-finite
        signals are skipped everywhere downstream — "no signal", never
        a crash, never a poisoned fingerprint."""
        ent, margin, rep = q
        if self._q_force_nan:
            ent = margin = float("nan")
        if math.isfinite(ent):
            self._q_entropy_hist.observe(ent)
        if math.isfinite(margin):
            self._q_margin_hist.observe(margin)
        self._quality_monitor.observe(ent, margin)
        acc = self._q_acc.get(rid)
        if acc is None:
            # ent_sum, ent_n, margin_sum, margin_n, rep_run, rep_max
            acc = self._q_acc[rid] = [0.0, 0, 0.0, 0, 0, 0]
        if math.isfinite(ent):
            acc[0] += ent
            acc[1] += 1
        if math.isfinite(margin):
            acc[2] += margin
            acc[3] += 1
        if rep > 0.5:
            acc[4] += 1
            acc[5] = max(acc[5], acc[4])
        else:
            acc[4] = 0

    def _emit(self, slot: Slot, token: int, now: float,
              finished: List[RequestOutput], lp=None, q=None) -> None:
        prev_token_t = slot.token_times[-1] if slot.token_times else None
        slot.generated.append(token)
        slot.token_times.append(now)
        if lp is not None:
            if slot.token_logprobs is None:
                slot.token_logprobs = []
                slot.top_logprobs = []
            slot.token_logprobs.append(lp[0])
            slot.top_logprobs.append(lp[1])
        if q is not None:
            self._q_observe(slot.request.request_id, q)
        if len(slot.generated) == 1:
            slot.first_token_time = now
            slot.state = ACTIVE
            self._ttft_hist.observe(now - slot.submit_time)
            self._class_ttft_hist.observe(
                now - slot.submit_time,
                priority=slot.request.params.priority,
            )
            if self._tracing:
                self.tracer.instant(
                    "first_token", rid=slot.request.request_id,
                    **(instant_args(slot.trace)
                       if slot.trace is not None else {}),
                )
        elif prev_token_t is not None:
            self._itl_hist.observe(now - prev_token_t)
            self._class_itl_hist.observe(
                now - prev_token_t,
                priority=slot.request.params.priority,
            )
        p = slot.request.params
        eos = (
            p.eos_token_id
            if p.eos_token_id is not None
            else self.serving.eos_token_id
        )
        hit_eos = eos is not None and token == eos
        stop_hit = False
        if not hit_eos and p.stop:
            g = slot.generated
            for seq in p.stop:
                n = len(seq)
                tail = g
                if len(g) < n and p.key_offset:
                    # replayed continuation: a stop sequence may span
                    # the prompt/generated boundary (its head was
                    # emitted by the dead attempt and rides the prompt
                    # tail) — match it exactly like the uninterrupted
                    # run would have
                    borrow = min(n - len(g), p.key_offset,
                                 slot.prompt_len)
                    P = slot.prompt_len
                    tail = [
                        int(t) for t in slot.prompt[P - borrow:P]
                    ] + g
                if len(tail) >= n and tuple(tail[-n:]) == seq:
                    stop_hit = True
                    break
        if hit_eos or stop_hit or len(slot.generated) >= p.max_new_tokens:
            finished.append(self._finish(
                slot,
                "eos" if hit_eos
                else ("stop_sequence" if stop_hit else "length"),
            ))
            return
        # the slot decodes on: keep its pipeline state current. The
        # histogram only exists once a penalized sample built it; the
        # FSM cursor follows every emitted token (the next step's mask
        # row — and the zero-row sweep — read it).
        if slot.penalty_counts is not None:
            slot.penalty_counts[token] += 1
        if slot.constraint is not None:
            slot.fsm_state = slot.constraint.advance(slot.fsm_state, token)

    def _finish(self, slot: Slot, reason: str,
                now: Optional[float] = None) -> RequestOutput:
        rid = slot.request.request_id
        quality = None
        if self._quality:
            acc = self._q_acc.pop(rid, None)
            quality = {
                "entropy_mean": (
                    round(acc[0] / acc[1], 6)
                    if acc and acc[1] else None
                ),
                "margin_mean": (
                    round(acc[2] / acc[3], 6)
                    if acc and acc[3] else None
                ),
                "tokens_observed": acc[1] if acc else 0,
                "rep_run_max": acc[5] if acc else 0,
            }
            if slot.spec_proposed:
                quality["spec_acceptance"] = round(
                    slot.spec_accepted / slot.spec_proposed, 4
                )
            if slot.request.params.constrained:
                # the validity rate the canary judge's quality axis
                # compares across arms: a dead end is the constrained
                # path's "wrong answer"
                self._q_constraint_total += 1
                if reason == "constraint_dead_end":
                    self._q_constraint_bad += 1
        out = RequestOutput(
            request_id=rid,
            prompt=[int(t) for t in slot.prompt],
            tokens=list(slot.generated),
            finish_reason=reason,
            submit_time=slot.submit_time,
            first_token_time=slot.first_token_time,
            # a slot retired at its deadline may not have produced a
            # single token yet (still prefilling)
            finish_time=(
                slot.token_times[-1] if slot.token_times
                else (now if now is not None else time.perf_counter())
            ),
            token_times=list(slot.token_times),
            trace_id=(
                slot.trace.trace_id if slot.trace is not None else None
            ),
            spec_proposed=slot.spec_proposed,
            spec_accepted=slot.spec_accepted,
            token_logprobs=(
                list(slot.token_logprobs)
                if slot.token_logprobs is not None else None
            ),
            top_logprobs=(
                list(slot.top_logprobs)
                if slot.top_logprobs is not None else None
            ),
            quality=quality,
        )
        if self._tracing:
            targs = (
                instant_args(slot.trace) if slot.trace is not None
                else {}
            )
            self.tracer.instant("finish", rid=out.request_id,
                                reason=reason, **targs)
            # the request's whole submit->finish lifetime as ONE span,
            # parented to the caller's traceparent hop — what the
            # stitched timeline lines up under the router's forward span
            sargs = (
                child_span_args(slot.trace) if slot.trace is not None
                else {}
            )
            self.tracer.complete(
                "request", slot.submit_time, out.finish_time,
                rid=out.request_id, reason=reason,
                tokens=len(out.tokens), **sargs,
            )
        del self._base_keys[slot.request.request_id]
        self._drop_constraint(slot.request.request_id)
        if reason == "deadline":
            self.stats.inc("deadline_expired")
        elif reason != "constraint_dead_end":
            # a dead end is a typed FAILURE delivery (HTTP 400 with
            # partial output), not a completion — it rides only the
            # labeled finished counter
            self.stats.inc("completed")
        self._finished_counter.inc(reason=reason)
        self.scheduler.retire(slot)
        return out

    def _expire_queued(self, request, prompt, submit_time: float,
                       now: float, trace=None) -> RequestOutput:
        """A request whose deadline passed while it waited for a slot:
        it never touches the device; the caller gets a typed error."""
        self._base_keys.pop(request.request_id, None)
        self._drop_constraint(request.request_id)
        self._drop_resume(request.request_id)
        self._q_acc.pop(request.request_id, None)
        self.stats.inc("deadline_expired")
        self._finished_counter.inc(reason="deadline")
        if self._tracing:
            self.tracer.instant(
                "finish", rid=request.request_id, reason="deadline",
                **(instant_args(trace) if trace is not None else {}),
            )
        return RequestOutput(
            request_id=request.request_id,
            prompt=[int(t) for t in prompt],
            tokens=[],
            finish_reason="deadline",
            submit_time=submit_time,
            first_token_time=0.0,
            finish_time=now,
            token_times=[],
            trace_id=trace.trace_id if trace is not None else None,
        )

    def _corrupt_one_slot(self) -> None:
        """Fault-injection helper (``serve_corrupt@N``): NaN-poison one
        occupied slot's KV rows. Prefers an ACTIVE slot — the ring mask
        derives visibility from position arithmetic, so poison in
        not-yet-written positions would stay invisible; an active
        slot's already-written keys are visible and the next decode
        step's logits go NaN, tripping the finite-logits guard."""
        target = next(
            (s for s in self.scheduler.slots if s.state == ACTIVE), None
        ) or next(
            (s for s in self.scheduler.slots
             if s.state != FREE and s.filled > 0), None
        )
        if target is None:
            return
        i = target.index
        if self._pages is not None:
            # paged layout: the slot's KV lives in the pages its table
            # row names, not at batch index i
            row = [
                int(p) for p in self._pages.table_row(i)
                if int(p) != PagePool.TRASH
            ]
            if row:
                self.cache = self._poison_pages(row)
            return

        def _poison(key, arr):
            idx = (slice(None), i) if KV_CACHE_BATCH_AXIS[key] else i
            if jnp.issubdtype(arr.dtype, jnp.floating):
                return arr.at[idx].set(jnp.nan)
            # int8 values cannot hold NaN; zeroing them while the float
            # scale planes go NaN above makes every dequantized read
            # 0 * NaN = NaN, so the finite-logits guard still trips
            return arr.at[idx].set(0)

        self.cache = [
            {key: _poison(key, c[key]) for key in c} for c in self.cache
        ]

    # -- crash recovery (serving/server.py supervision) ----------------

    def reset_after_crash(self) -> List[int]:
        """Rebuild device-side state after a failed :meth:`step`.

        A crashed step leaves the engine untrusted: the jitted calls
        donate the cache pool, so a failure mid-call may have
        invalidated (or poisoned) it. Params are immutable jax arrays —
        never donated, never written — so the pool is rebuilt from
        scratch exactly as ``__init__`` built it, and the jitted
        closures are reused from the module-level cache (a restart adds
        ZERO recompiles; pinned by tests/test_serving_resilience.py).

        Requests that held slots (in-flight) lost device state and are
        FAILED — their request_ids are returned for the supervisor to
        error out with :class:`EngineCrashError`. Requests still in the
        wait queue never touched the device and are preserved verbatim
        (same request_id, prompt, deadline, PRNG base), so they complete
        normally after the restart. Stats survive;
        ``stats["engine_restarts"]`` counts the rebuilds.
        """
        if self._device_prof is not None:
            # a crash mid-capture leaves the profiler window open; the
            # torn trace is dropped (counted) so the rebuilt engine's
            # next due iteration captures normally
            self._device_prof.abort()
        lost: List[int] = []
        for slot in self.scheduler.slots:
            if slot.state != FREE and slot.request is not None:
                rid = slot.request.request_id
                lost.append(rid)
                self._base_keys.pop(rid, None)
                self._drop_constraint(rid)
                self._drop_resume(rid)
                self._q_acc.pop(rid, None)
        preserved = list(self.scheduler.queue)
        self._resumed = []
        if self._tier is not None:
            # host-cached prefixes are as untrusted as the device pool
            # they were captured from (a poisoned page demotes with a
            # VALID checksum — the CRC guards torn transfers, not
            # upstream corruption). Preempted requests' stashes
            # SURVIVE: their owners ride the preserved queue and
            # resume bit-exact on the rebuilt engine.
            self._tier.clear_cache()
        if self._paged:
            # fresh page pool AND an empty radix cache: untrusted KV
            # includes every cached prefix (the poisoned-prefix fault's
            # eviction path), so nothing cached survives a crash
            self._pages.reset()
            self.cache = init_cache_paged(
                self.cfg, self._pages.total_pages,
                self.serving.kv_page_size,
            )
        else:
            self.cache = init_cache(self.cfg, self._rows)
        if self._drafter is not None:
            # fresh drafter pool from params: its KV is as untrusted
            # as the target's after a crash, and the rebuild costs
            # zero recompiles (module-cached closures)
            self._drafter.reset()
        self.scheduler = Scheduler(
            self.serving,
            on_retire=(
                self._on_retire
                if (self._paged or self._spec_k) else None
            ),
            on_preempt=(
                self._preempt_slot if self._tier is not None else None
            ),
        )
        self.scheduler.queue.extend(preserved)
        self.stats.inc("engine_restarts")
        # the crashed step never reached its gauge refresh; bring the
        # point-in-time view in line with the rebuilt (empty) slot pool
        self._update_gauges()
        return lost
