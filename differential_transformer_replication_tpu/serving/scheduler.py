"""Admission + iteration-level scheduling for the serving engine.

Orca-style continuous batching: scheduling decisions are made per
ITERATION, not per request. Each call to :meth:`Scheduler.plan` (one
engine step) does two things, both FCFS:

1. **Admission** — queued requests move into FREE slots of the fixed
   pool while any are free. A request occupies exactly one slot from
   admission to retirement; the pool size never grows, so the decode
   batch shape is static and admissions never recompile.
2. **Prefill planning** — slots still prefilling advance by at most
   ``prefill_budget`` prompt tokens per iteration, split into
   descending power-of-two chunks no larger than ``prefill_chunk``.
   The budget is the fairness knob: without it, one block_size-long
   prompt would stall every decoding sequence for its whole prefill
   (the "prefill starves decode" failure mode Orca's iteration-level
   scheduling exists to fix). The power-of-two ladder bounds the set of
   chunk shapes that ever compile to log2(prefill_chunk)+1.

The scheduler is pure host-side bookkeeping — slot state, queue, stats.
Device work (the actual chunk/decode calls) lives in serving/engine.py.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from differential_transformer_replication_tpu.config import ServingConfig
from differential_transformer_replication_tpu.serving.request import (
    PRIORITY_CLASSES,
    PRIORITY_RANK,
    Request,
)

FREE = "free"
PREFILL = "prefill"
ACTIVE = "active"


class QueueFullError(RuntimeError):
    """Admission rejected: the wait queue is at ``max_queue_len``. The
    graceful-overload contract — callers get an immediate, retryable
    error (HTTP 503 from the server) instead of an unbounded wait."""

    retriable = True


class DeadlineExceededError(RuntimeError):
    """The request's server-side deadline expired before completion.

    Raised to the CALLER only (serving/server.py delivers it, HTTP 504);
    engine-side the request is shed at admission or retired mid-decode
    so its KV slot goes back to the pool instead of decoding for a
    client that has already given up. ``output`` carries the partial
    :class:`RequestOutput` (``finish_reason == "deadline"``; tokens
    generated before expiry, empty when shed at admission)."""

    def __init__(self, message: str, output=None):
        super().__init__(message)
        self.output = output


@dataclass
class Slot:
    """One KV-cache slot's host-side state."""

    index: int
    state: str = FREE
    request: Optional[Request] = None
    prompt: Optional[np.ndarray] = None  # cropped prompt actually run
    filled: int = 0  # prompt tokens already prefilled
    # prompt tokens whose KV the radix prefix cache already held at
    # admission (serving/pages.py): prefill starts here, and the
    # engine's queue-wait/TTFT instrumentation keys the first RUN
    # chunk on it. Always 0 on the contiguous path.
    cached_len: int = 0
    generated: List[int] = field(default_factory=list)
    admit_seq: int = -1  # admission order, for FCFS prefill within a step
    submit_time: float = 0.0
    # absolute perf_counter() deadline; 0.0 = none. The engine retires
    # the slot (reason "deadline") once now >= deadline, mid-decode.
    deadline: float = 0.0
    first_token_time: float = 0.0
    token_times: List[float] = field(default_factory=list)
    # the request's cross-process trace context
    # (obs/trace.py:TraceContext), or None when it arrived untraced —
    # pure host-side bookkeeping, stamped onto span/instant args only
    trace: Optional[object] = None
    # speculative-decoding accounting (serving/spec.py): draft tokens
    # proposed/accepted for this request so far — copied onto the
    # RequestOutput at retirement
    spec_proposed: int = 0
    spec_accepted: int = 0
    # the cropped prompt as a plain int list, built lazily by the
    # engine's proposal collector — per-element int() conversion of
    # the numpy prompt every decode iteration was measurable hot-loop
    # host cost
    prompt_ids: Optional[list] = None
    # structured decoding (serving/constrain.py): the compiled token
    # FSM (attached lazily by the engine on first hot-path touch, so
    # unconstrained slots never pay the cache lookup) and the cursor
    # into its state table, advanced host-side per emitted token.
    # fsm_state -1 is the dead-end sentinel (all-zero mask row) — only
    # the constrain_dead_end fault plants it; compiled FSMs prune dead
    # states so natural generation cannot reach one.
    constraint: Optional[object] = None
    fsm_state: int = 0
    # generated-token occurrence counts for the repetition/presence/
    # frequency penalties — a (V,) int32 histogram, allocated lazily
    # (None for requests with every penalty off)
    penalty_counts: Optional[np.ndarray] = None
    # logprob echo accumulators (SamplingParams.logprobs > 0): chosen
    # token's logprob and top-N (id, logprob) pairs per emitted token
    token_logprobs: Optional[list] = None
    top_logprobs: Optional[list] = None

    @property
    def prompt_len(self) -> int:
        return 0 if self.prompt is None else int(self.prompt.shape[0])

    def reset(self) -> None:
        self.state = FREE
        self.request = None
        self.prompt = None
        self.filled = 0
        self.cached_len = 0
        self.generated = []
        self.admit_seq = -1
        self.submit_time = 0.0
        self.deadline = 0.0
        self.first_token_time = 0.0
        self.token_times = []
        self.trace = None
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.prompt_ids = None
        self.constraint = None
        self.fsm_state = 0
        self.penalty_counts = None
        self.token_logprobs = None
        self.top_logprobs = None


def _pow2_chunk(n: int, cap: int) -> int:
    """Largest power of two <= min(n, cap); n, cap >= 1."""
    m = min(n, cap)
    return 1 << (m.bit_length() - 1)


class Scheduler:
    """FCFS queue + slot pool bookkeeping (see module docstring)."""

    def __init__(self, serving: ServingConfig, on_retire=None,
                 on_preempt=None):
        self.serving = serving
        # retirement hook: called with the slot BEFORE it resets, on
        # EVERY retire path (finish, deadline, cancel) — how the paged
        # engine returns KV pages / inserts prompts into the radix
        # cache (serving/engine.py:_release_slot_pages). None = no-op.
        self.on_retire = on_retire
        # preemption hook (serving/engine.py:_preempt_slot, set only
        # when the host tier is on): called with an ACTIVE victim slot
        # when a strictly better-ranked request is blocked on pages.
        # The engine stashes the victim's KV to the host tier, releases
        # its pages, REQUEUES it (original submit_time, so aging keeps
        # accruing) and resets the slot. None = no preemption.
        self.on_preempt = on_preempt
        self.slots = [Slot(index=i) for i in range(serving.num_slots)]
        # (request, cropped prompt, submit_time, deadline, trace) —
        # deadline is an absolute perf_counter() timestamp, 0.0 = none;
        # trace is the request's TraceContext or None
        self.queue: Deque[
            Tuple[Request, np.ndarray, float, float, Optional[object]]
        ] = deque()
        self._admit_seq = 0
        # invariant checked by tests: concurrent occupied slots never
        # exceed the pool
        self.max_concurrent = 0

    # -- submission ---------------------------------------------------

    def submit(self, request: Request, prompt: np.ndarray,
               submit_time: float, deadline: float = 0.0,
               trace: Optional[object] = None) -> None:
        """Enqueue an engine-validated (request, cropped prompt) pair.
        Raises :class:`QueueFullError` when the wait queue is at
        ``max_queue_len`` (0 = unbounded): overload must degrade into
        fast rejections, not an ever-growing queue of requests that will
        all miss their caller's deadline anyway."""
        maxq = self.serving.max_queue_len
        if maxq and len(self.queue) >= maxq:
            raise QueueFullError(
                f"admission queue full ({len(self.queue)}/{maxq} waiting, "
                f"{self.occupied()}/{len(self.slots)} slots busy); retry "
                "later"
            )
        self.queue.append((request, prompt, submit_time, deadline, trace))

    def cancel(self, request_id: int) -> bool:
        """Remove a request wherever it lives: still waiting (dropped
        from the queue) or holding a slot (the slot is retired, so its
        KV rows go back to the pool for the next admission). Returns
        whether the request was found."""
        for i, entry in enumerate(self.queue):
            if entry[0].request_id == request_id:
                del self.queue[i]
                return True
        for slot in self.slots:
            if slot.state != FREE and slot.request.request_id == request_id:
                self.retire(slot)
                return True
        return False

    # -- queries ------------------------------------------------------

    def has_work(self) -> bool:
        return bool(self.queue) or any(s.state != FREE for s in self.slots)

    def queue_len(self) -> int:
        return len(self.queue)

    def queue_depths(self) -> Dict[str, int]:
        """Waiting requests per priority class — the per-class queue
        depth EngineRunner surfaces on /health and /metrics."""
        depths = {c: 0 for c in PRIORITY_CLASSES}
        for e in self.queue:
            depths[e[0].params.priority] += 1
        return depths

    def free_slots(self) -> List[Slot]:
        return [s for s in self.slots if s.state == FREE]

    def active_slots(self) -> List[Slot]:
        return [s for s in self.slots if s.state == ACTIVE]

    def occupied(self) -> int:
        return sum(1 for s in self.slots if s.state != FREE)

    # -- deadlines ----------------------------------------------------

    def shed_expired(self, now: float) -> List[
        Tuple[Request, np.ndarray, float, float, Optional[object]]
    ]:
        """Drop already-expired entries from the wait queue and return
        them. Admission-time shedding: a request whose deadline passed
        while it waited would burn prefill + decode iterations for a
        caller that has already given up — it never gets a slot. The
        engine converts the returned entries into ``finish_reason ==
        "deadline"`` outputs (a typed error at the caller)."""
        if not any(e[3] and now >= e[3] for e in self.queue):
            return []
        expired = [e for e in self.queue if e[3] and now >= e[3]]
        self.queue = deque(
            e for e in self.queue if not (e[3] and now >= e[3])
        )
        return expired

    def expired_slots(self, now: float) -> List[Slot]:
        """Occupied slots whose request's deadline has passed — the
        engine retires these (KV rows back to the pool) instead of
        decoding for nobody. Does not mutate; retirement is the
        engine's move (it must emit the partial output first)."""
        return [
            s for s in self.slots
            if s.state != FREE and s.deadline and now >= s.deadline
        ]

    # -- the per-iteration decision -----------------------------------

    def _effective_rank(self, priority: str, submit_time: float,
                        now: float) -> float:
        """Class rank with anti-starvation aging: every
        ``priority_aging_s`` seconds waited improves the rank by one
        class, so a starved batch request eventually outranks fresh
        high-priority traffic (bounded starvation by construction)."""
        rank = float(PRIORITY_RANK.get(priority, 1))
        aging = self.serving.priority_aging_s
        if aging > 0:
            rank -= int(max(now - submit_time, 0.0) / aging)
        return rank

    def _preempt_victim(self, blocked_rank: float,
                        now: float) -> Optional[Slot]:
        """The ACTIVE slot with the WORST effective rank, provided it
        is STRICTLY worse than the blocked request's — equal-class
        peers never preempt each other, so all-one-class traffic
        degrades exactly like the pre-priority FCFS engine."""
        worst, worst_rank = None, blocked_rank
        for s in self.slots:
            if s.state != ACTIVE:
                continue
            r = self._effective_rank(
                s.request.params.priority, s.submit_time, now
            )
            if r > worst_rank:
                worst, worst_rank = s, r
        return worst

    def plan(self, admit=None) -> List[Tuple[Slot, int, int]]:
        """Admit + plan this iteration's prefill work.

        Returns ``[(slot, start, length), ...]`` chunks (FCFS by
        admission order, budget-capped); the engine executes them in
        order and flips a slot to ACTIVE when its prompt completes.

        Admission is priority-aware: each round picks the queued
        request with the best (effective rank, queue position) — aging
        per :meth:`_effective_rank` — skipping classes at their
        ``priority_max_slots`` bound. All-normal traffic reduces
        exactly to the old FCFS order.

        ``admit`` is the paged engine's admission gate: called with
        ``(slot, queue_entry)`` for the selected request BEFORE it is
        committed, it returns the cached prefix length to skip (>= 0,
        prefill starts there), None to keep the request queued (free
        pages exhausted), or -1 when the gate consumed the entry
        itself (typed shed). On None, if a preemption hook is set and
        an ACTIVE slot ranks strictly worse than the blocked request,
        that victim is preempted (its pages stash to the host tier)
        and the gate retried; otherwise admission stops for this
        iteration — blocking preserves rank order. None gate = admit
        unconditionally (the contiguous path).
        """
        bounds = self.serving.priority_slot_bounds()
        now = time.perf_counter()
        while self.queue:
            free = [s for s in self.slots if s.state == FREE]
            if not free:
                break
            # per-class occupancy for the admission bounds; recomputed
            # each round (admissions and preemptions change it)
            occ: Dict[str, int] = {}
            for s in self.slots:
                if s.state != FREE:
                    cls = s.request.params.priority
                    occ[cls] = occ.get(cls, 0) + 1
            best_i, best_key = None, None
            for i, e in enumerate(self.queue):
                cls = e[0].params.priority
                if cls in bounds and occ.get(cls, 0) >= bounds[cls]:
                    continue
                key = (self._effective_rank(cls, e[2], now), i)
                if best_key is None or key < best_key:
                    best_i, best_key = i, key
            if best_i is None:
                break  # every waiting class is at its slot bound
            slot = free[0]
            entry = self.queue[best_i]
            cached = 0
            if admit is not None:
                verdict = admit(slot, entry)
                if verdict is None:
                    if self.on_preempt is not None:
                        victim = self._preempt_victim(best_key[0], now)
                        if victim is not None:
                            # the hook stashes KV, releases pages,
                            # requeues the victim and resets the slot;
                            # retry the gate against the freed pages
                            self.on_preempt(victim)
                            continue
                    break
                if verdict < 0:
                    del self.queue[best_i]
                    continue
                cached = verdict
            del self.queue[best_i]
            request, prompt, t_submit, deadline, trace = entry
            slot.state = PREFILL
            slot.request = request
            slot.prompt = prompt
            slot.filled = cached
            slot.cached_len = cached
            slot.generated = []
            slot.token_times = []
            slot.spec_proposed = 0
            slot.spec_accepted = 0
            slot.prompt_ids = None
            slot.constraint = None
            slot.fsm_state = 0
            slot.penalty_counts = None
            slot.token_logprobs = None
            slot.top_logprobs = None
            slot.submit_time = t_submit
            slot.deadline = deadline
            slot.trace = trace
            slot.admit_seq = self._admit_seq
            self._admit_seq += 1
        self.max_concurrent = max(self.max_concurrent, self.occupied())

        budget = self.serving.prefill_budget
        chunks: List[Tuple[Slot, int, int]] = []
        pending = sorted(
            (s for s in self.slots if s.state == PREFILL),
            key=lambda s: s.admit_seq,
        )
        for slot in pending:
            start = slot.filled
            while budget > 0 and start < slot.prompt_len:
                size = _pow2_chunk(
                    min(slot.prompt_len - start, budget),
                    self.serving.prefill_chunk,
                )
                chunks.append((slot, start, size))
                start += size
                budget -= size
            if budget <= 0:
                break
        return chunks

    # -- retirement ---------------------------------------------------

    def retire(self, slot: Slot) -> None:
        """Return a slot to the FREE pool. The KV rows need no clearing:
        the ring mask derives visibility purely from position arithmetic
        (models/decode.py:_attn_chunk), so a fresh prefill at pos=0
        masks every stale key the previous occupant left behind. The
        ``on_retire`` hook (paged engine) sees the slot first — every
        retire path (finish, deadline, cancel) releases its pages."""
        if self.on_retire is not None and slot.state != FREE:
            self.on_retire(slot)
        slot.reset()
