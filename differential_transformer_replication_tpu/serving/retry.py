"""Client-side retry with jittered exponential backoff.

The server sheds load with TYPED, retriable failures — HTTP 503 with a
``Retry-After`` header (queue full, draining, engine restarting), or
:class:`~.scheduler.QueueFullError` / :class:`~.engine.EngineCrashError`
in-process. A client that retries those naively in a tight loop defeats
the shedding (everyone re-piles-on at once); one that never retries
turns a transient restart into a user-visible failure. This module is
the well-behaved middle: full-jitter exponential backoff (the AWS
architecture-blog scheme: sleep ~ Uniform(0, min(cap, base*2^attempt)),
which decorrelates a thundering herd), FLOORED by the server's
``Retry-After`` when it sent one — the server knows how long its drain
or restart backoff actually is.

Pure stdlib, no jax import: usable from any client (and from
tools/serve_bench.py, whose error-breakdown output these helpers feed).
"""

from __future__ import annotations

import json
import random
import time
import urllib.error
import urllib.request
from typing import Callable, Optional, Tuple


def backoff_delay(attempt: int, base: float = 0.2, cap: float = 5.0,
                  retry_after: Optional[float] = None,
                  rng: Optional[random.Random] = None) -> float:
    """Seconds to sleep before retry number ``attempt`` (0-based).

    Full jitter over the exponential envelope, floored by the server's
    ``Retry-After`` when given — honoring it keeps clients from hammering
    a replica that told them exactly when it will be back.
    """
    envelope = min(cap, base * (2 ** attempt))
    delay = (rng or random).uniform(0.0, envelope)
    if retry_after is not None:
        delay = max(delay, retry_after)
    return delay


def call_with_retries(fn: Callable, max_retries: int = 3,
                      base: float = 0.2, cap: float = 5.0,
                      retriable: Tuple[type, ...] = (),
                      rng: Optional[random.Random] = None,
                      sleep: Callable[[float], None] = time.sleep):
    """Run ``fn()`` retrying typed retriable failures; returns
    ``(result, retries_used)``. An exception carrying a ``retry_after``
    attribute (seconds) floors that retry's backoff; one whose
    ``retriable`` attribute is False is re-raised immediately even when
    its TYPE matches (a permanently failed engine raises the same class
    as a restarting one). The final attempt's exception propagates with
    ``retry_attempts`` set to the attempts burned — callers see the
    TYPED error, never a hang, and can still account for the retries."""
    attempt = 0
    while True:
        try:
            return fn(), attempt
        except retriable as e:
            if attempt >= max_retries or not getattr(e, "retriable", True):
                e.retry_attempts = attempt
                raise
            sleep(backoff_delay(
                attempt, base, cap,
                retry_after=getattr(e, "retry_after", None), rng=rng,
            ))
            attempt += 1


def http_post_json_with_retries(
    url: str, payload: dict, timeout: float = 600.0,
    max_retries: int = 3, base: float = 0.2, cap: float = 5.0,
    rng: Optional[random.Random] = None,
    sleep: Callable[[float], None] = time.sleep,
    deadline_s: Optional[float] = None,
    retry_after_cap: float = 30.0,
    clock: Callable[[], float] = time.monotonic,
) -> Tuple[int, dict, int]:
    """POST JSON, retrying retriable 503s (honoring ``Retry-After``)
    and transport errors with jittered backoff; returns
    ``(status, body, retries)``.

    Non-retriable statuses (400, 404, 500, 504 — a missed deadline
    will not be met by retrying either) return immediately, as does a
    503 whose body ``code`` marks it non-retriable: ``timeout`` (the
    request already burned its full generation budget; re-adding that
    load to a server at its slowest only amplifies the overload) and
    ``engine_failed`` (the replica will never recover — fail over). A
    503 with no ``code`` (a proxy, a different server) is treated as
    retriable. When the retry budget runs out the last 503 is returned
    as its status (or raised with ``retry_attempts`` set, for transport
    errors) rather than hidden.

    ``deadline_s`` budgets TOTAL elapsed time (attempts + backoffs)
    against the same deadline the server enforces: a retry whose
    backoff would land past it is not taken — the server would only
    answer 504 — and each attempt's transport timeout is clamped to
    the time remaining. Honored ``Retry-After`` values are capped at
    ``retry_after_cap`` seconds so a long drain budget (or a buggy
    header) can never park the client longer than its own deadline
    policy allows; the jittered-backoff envelope is unaffected.
    ``clock`` is injectable for tests (pairs with ``sleep``).
    """
    attempt = 0
    end = None if deadline_s is None else clock() + deadline_s
    while True:
        retry_after = None
        try:
            attempt_timeout = timeout
            if end is not None:
                attempt_timeout = max(0.001, min(timeout, end - clock()))
            req = urllib.request.Request(
                url, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=attempt_timeout) as r:
                return r.status, json.load(r), attempt
        except urllib.error.HTTPError as e:
            body = {}
            try:
                body = json.loads(e.read() or b"{}")
            except (ValueError, OSError):
                pass
            final = (
                e.code != 503
                or body.get("code") in ("timeout", "engine_failed")
                or attempt >= max_retries
            )
            if final:
                return e.code, body, attempt
            ra = e.headers.get("Retry-After")
            if ra is not None:
                try:
                    retry_after = min(float(ra), retry_after_cap)
                except ValueError:
                    pass
            delay = backoff_delay(attempt, base, cap,
                                  retry_after=retry_after, rng=rng)
            if end is not None and clock() + delay >= end:
                # the deadline would expire mid-backoff: surface the
                # last typed 503 now instead of retrying into a 504
                return e.code, body, attempt
        except (urllib.error.URLError, TimeoutError, ConnectionError,
                ValueError) as e:
            # transport-level: the server may be mid-restart; retry on
            # the same schedule, raise when the budget runs out.
            # ValueError covers a 200 whose body arrives truncated or
            # garbled (a server killed mid-response) — same class of
            # failure as the connection dying outright
            if attempt >= max_retries:
                e.retry_attempts = attempt
                raise
            delay = backoff_delay(attempt, base, cap, rng=rng)
            if end is not None and clock() + delay >= end:
                e.retry_attempts = attempt
                raise
        sleep(delay)
        attempt += 1
