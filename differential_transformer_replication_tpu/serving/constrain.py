"""Constraint compiler for structured decoding (Willard & Louf 2023).

The serving engine historically ran exactly one workload: free-running
sampling. Agent/tool-calling traffic needs the model's output to be
*machine-parseable* — valid JSON against a schema, a match of a regex,
one of an enumerated set of strings — and the only way to guarantee
that at temperature > 0 is to make invalid tokens unsamplable. This
module is the host-side half of that guarantee, following the FSM
blueprint of Willard & Louf 2023 ("Efficient Guided Generation for
Large Language Models", arXiv:2307.09702, PAPERS.md): compile the
constraint ONCE into a token-level finite-state machine —

- ``masks``:  (S, V) bool — ``masks[s, t]`` = emitting token ``t`` in
  state ``s`` keeps the output a prefix of the constrained language;
- ``trans``:  (S, V) int32 — the state after emitting ``t`` in ``s``
  (-1 where disallowed);
- ``accepting``: (S,) bool — states where the constraint is satisfied
  (the request's EOS token, when configured, is allowed exactly here)

— so the decode hot path never walks a grammar: the engine gathers one
precomputed mask row per constrained slot per step (a table lookup),
applies it inside the jitted pool step as a runtime array (zero
recompiles), and advances the cursor with one ``trans[s, t]`` read per
emitted token.

Three constraint families compile to the same FSM:

- **regex** — a deliberately small, dependency-free engine (literals,
  classes ``[a-z0-9]`` with ranges/negation, ``.``, ``* + ?``,
  ``{m}``/``{m,n}`` bounded repeats, alternation, groups, and the
  ``\\d \\w \\s`` escapes) lowered Thompson-style to an NFA, then
  subset-constructed to a char-level DFA;
- **JSON Schema** (subset) — lowered to a regex over the *canonical
  compact* serialization (no whitespace, object properties in declared
  order, all required): ``string`` (escape-free), ``integer``,
  ``number``, ``boolean``, ``null``, ``enum``/``const``, nested
  ``object``/``array``;
- **choices** — an escaped-literal alternation (the tool-calling
  "pick one of these strings" case).

The token-level FSM then comes from walking every vocabulary token's
STRING through the char DFA from every live state (dead states — no
path to an accepting state — are pruned first, so a well-formed
constraint can never dead-end naturally; an all-zero mask row only
ever comes from the ``constrain_dead_end`` fault or a poisoned
cursor, and the engine retires it typed, never hangs).

Like serving/pages.py and serving/router.py this module never imports
jax: compile and cache are pure host state. :class:`ConstraintCache`
refcounts compiled FSMs across concurrent requests exactly like the
radix prefix cache refcounts KV pages — same spec + same EOS = same
tables, byte-accounted, LRU-evicted only at refcount 0 — and is a
lock-owning class in the GL301 sense (the engine thread acquires/
releases while /health readers call :meth:`stats`).
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from differential_transformer_replication_tpu.utils import faults


class ConstraintCompileError(ValueError):
    """The constraint spec cannot be compiled (malformed regex,
    unsupported schema construct, empty language, vocabulary that
    cannot spell the constraint). A ValueError so every submit-path
    funnel (HTTP 400, engine submit) treats it as caller error; typed
    so serving/server.py can attach the machine-readable
    ``constraint_compile_failed`` code. The engine is untouched: a
    failed compile happens before the scheduler ever sees the
    request."""


class ConstraintDeadEndError(RuntimeError):
    """A constrained request reached an FSM state with an all-zero
    token mask mid-generation: nothing it could emit would keep the
    output inside the constrained language. RETRIABLE (a fresh seed or
    a fixed constraint may complete); ``output`` carries the partial
    :class:`~.request.RequestOutput` (``finish_reason ==
    "constraint_dead_end"``). The engine retires the slot — pages and
    KV rows reclaimed through the standard retire path — and the
    server maps this to HTTP 400 ``constraint_dead_end`` with the
    partial tokens, never a hang or a garbage token."""

    retriable = True

    def __init__(self, message: str, output=None):
        super().__init__(message)
        self.output = output


# ---------------------------------------------------------------------
# regex -> char-level NFA (Thompson construction) -> DFA (subset)
# ---------------------------------------------------------------------

_EPS = None  # epsilon edge label


class _Nfa:
    """Fragment with one start state and one accept state. States are
    integers into ``edges``: state -> list of (label, target) where
    label is a frozenset of chars or _EPS."""

    def __init__(self):
        self.edges: List[List[Tuple[Optional[frozenset], int]]] = []

    def state(self) -> int:
        self.edges.append([])
        return len(self.edges) - 1

    def edge(self, src: int, label, dst: int) -> None:
        self.edges[src].append((label, dst))


_CLASS_ESCAPES = {
    "d": frozenset("0123456789"),
    "w": frozenset(
        "abcdefghijklmnopqrstuvwxyz"
        "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_"
    ),
    "s": frozenset(" \t\n\r\f\v"),
}

# "." and negated classes need a concrete universe; printable ASCII +
# whitespace covers every tokenizer this repo ships (byte-level BPE
# over TinyStories) and every JSON/regex constraint a test can pose
_UNIVERSE = frozenset(chr(c) for c in range(32, 127)) | frozenset("\t\n\r")


class _RegexParser:
    """Recursive-descent parser producing an NFA fragment. Grammar:

    alt     := concat ('|' concat)*
    concat  := repeat*
    repeat  := atom ('*' | '+' | '?' | '{m}' | '{m,n}')?
    atom    := literal | escape | '.' | class | '(' alt ')'
    """

    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0
        self.nfa = _Nfa()

    def parse(self) -> Tuple[_Nfa, int, int]:
        start, end = self._alt()
        if self.i != len(self.p):
            raise ConstraintCompileError(
                f"regex parse error at position {self.i} in {self.p!r}"
            )
        return self.nfa, start, end

    # -- helpers ------------------------------------------------------

    def _peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def _take(self) -> str:
        ch = self._peek()
        if ch is None:
            raise ConstraintCompileError(
                f"unexpected end of regex {self.p!r}"
            )
        self.i += 1
        return ch

    # -- productions --------------------------------------------------

    def _alt(self) -> Tuple[int, int]:
        frags = [self._concat()]
        while self._peek() == "|":
            self._take()
            frags.append(self._concat())
        if len(frags) == 1:
            return frags[0]
        s, e = self.nfa.state(), self.nfa.state()
        for fs, fe in frags:
            self.nfa.edge(s, _EPS, fs)
            self.nfa.edge(fe, _EPS, e)
        return s, e

    def _concat(self) -> Tuple[int, int]:
        start = prev_end = None
        while self._peek() is not None and self._peek() not in "|)":
            fs, fe = self._repeat()
            if start is None:
                start, prev_end = fs, fe
            else:
                self.nfa.edge(prev_end, _EPS, fs)
                prev_end = fe
        if start is None:  # empty branch: epsilon fragment
            s = self.nfa.state()
            return s, s
        return start, prev_end

    def _repeat(self) -> Tuple[int, int]:
        frag_start = self.i
        fs, fe = self._atom()
        op = self._peek()
        if op == "*" or op == "+":
            self._take()
            s, e = self.nfa.state(), self.nfa.state()
            self.nfa.edge(s, _EPS, fs)
            self.nfa.edge(fe, _EPS, fs)
            self.nfa.edge(fe, _EPS, e)
            if op == "*":
                self.nfa.edge(s, _EPS, e)
            return s, e
        if op == "?":
            self._take()
            s, e = self.nfa.state(), self.nfa.state()
            self.nfa.edge(s, _EPS, fs)
            self.nfa.edge(fe, _EPS, e)
            self.nfa.edge(s, _EPS, e)
            return s, e
        if op == "{":
            atom_src = self.p[frag_start:self.i]
            self._take()
            spec = ""
            while self._peek() not in ("}", None):
                spec += self._take()
            if self._peek() is None:
                raise ConstraintCompileError(
                    f"unterminated {{m,n}} in {self.p!r}"
                )
            self._take()
            lo, _, hi = spec.partition(",")
            try:
                m = int(lo)
                n = m if not _ else (int(hi) if hi else None)
            except ValueError:
                raise ConstraintCompileError(
                    f"bad repeat spec {{{spec}}} in {self.p!r}"
                ) from None
            if n is None:  # {m,} == atom{m} atom*
                expanded = atom_src * m + atom_src + "*"
            else:
                if n < m:
                    raise ConstraintCompileError(
                        f"bad repeat bounds {{{spec}}} in {self.p!r}"
                    )
                expanded = atom_src * m + (atom_src + "?") * (n - m)
            sub = _RegexParser(expanded)
            sub.nfa = self.nfa
            sub_s, sub_e = sub._alt()
            if sub.i != len(expanded):
                raise ConstraintCompileError(
                    f"regex parse error expanding {{{spec}}} in "
                    f"{self.p!r}"
                )
            return sub_s, sub_e
        return fs, fe

    def _atom(self) -> Tuple[int, int]:
        ch = self._take()
        if ch == "(":
            fs, fe = self._alt()
            if self._peek() != ")":
                raise ConstraintCompileError(
                    f"unbalanced '(' in {self.p!r}"
                )
            self._take()
            return fs, fe
        if ch == "[":
            return self._char_class()
        if ch == ".":
            return self._label(_UNIVERSE)
        if ch == "\\":
            return self._label(self._escape_set(self._take()))
        if ch in "*+?{":
            raise ConstraintCompileError(
                f"dangling quantifier {ch!r} in {self.p!r}"
            )
        if ch in ")|":
            raise ConstraintCompileError(
                f"unexpected {ch!r} in {self.p!r}"
            )
        return self._label(frozenset(ch))

    def _escape_set(self, ch: str) -> frozenset:
        if ch in _CLASS_ESCAPES:
            return _CLASS_ESCAPES[ch]
        if ch == "n":
            return frozenset("\n")
        if ch == "t":
            return frozenset("\t")
        if ch == "r":
            return frozenset("\r")
        return frozenset(ch)  # \. \\ \[ \{ \" ...

    def _char_class(self) -> Tuple[int, int]:
        negate = self._peek() == "^"
        if negate:
            self._take()
        chars: set = set()
        first = True
        while True:
            ch = self._peek()
            if ch is None:
                raise ConstraintCompileError(
                    f"unterminated '[' in {self.p!r}"
                )
            if ch == "]" and not first:
                self._take()
                break
            first = False
            self._take()
            if ch == "\\":
                chars |= self._escape_set(self._take())
                continue
            if self._peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self._take()
                hi = self._take()
                if hi == "\\":
                    hi = self._take()
                if ord(hi) < ord(ch):
                    raise ConstraintCompileError(
                        f"bad range {ch}-{hi} in {self.p!r}"
                    )
                chars |= {chr(c) for c in range(ord(ch), ord(hi) + 1)}
            else:
                chars.add(ch)
        label = (
            _UNIVERSE - frozenset(chars) if negate else frozenset(chars)
        )
        return self._label(label)

    def _label(self, chars: frozenset) -> Tuple[int, int]:
        s, e = self.nfa.state(), self.nfa.state()
        self.nfa.edge(s, chars, e)
        return s, e


class CharDfa:
    """Char-level DFA: ``step[state].get(ch)`` -> next state;
    ``accepting`` is a set of state indices; state 0 is the start."""

    def __init__(self, step: List[Dict[str, int]], accepting: set):
        self.step = step
        self.accepting = accepting

    def matches(self, text: str) -> bool:
        s = 0
        for ch in text:
            s = self.step[s].get(ch, -1)
            if s < 0:
                return False
        return s in self.accepting


def compile_regex(pattern: str) -> CharDfa:
    """Regex -> char DFA via Thompson NFA + subset construction, with
    unreachable/dead states never materialized (subset construction
    only visits reachable sets; dead-state trimming happens at the
    token-FSM level where acceptance-reachability is decided)."""
    nfa, start, end = _RegexParser(pattern).parse()

    def _closure(states: frozenset) -> frozenset:
        stack, seen = list(states), set(states)
        while stack:
            s = stack.pop()
            for label, dst in nfa.edges[s]:
                if label is _EPS and dst not in seen:
                    seen.add(dst)
                    stack.append(dst)
        return frozenset(seen)

    start_set = _closure(frozenset([start]))
    index = {start_set: 0}
    worklist = [start_set]
    step: List[Dict[str, int]] = [{}]
    accepting: set = set()
    if end in start_set:
        accepting.add(0)
    while worklist:
        cur = worklist.pop()
        ci = index[cur]
        by_char: Dict[str, set] = {}
        for s in cur:
            for label, dst in nfa.edges[s]:
                if label is _EPS:
                    continue
                for ch in label:
                    by_char.setdefault(ch, set()).add(dst)
        for ch, dsts in by_char.items():
            nxt = _closure(frozenset(dsts))
            ni = index.get(nxt)
            if ni is None:
                ni = len(step)
                index[nxt] = ni
                step.append({})
                worklist.append(nxt)
                if end in nxt:
                    accepting.add(ni)
            step[ci][ch] = ni
    return CharDfa(step, accepting)


# ---------------------------------------------------------------------
# JSON Schema (subset) -> regex over the canonical compact serialization
# ---------------------------------------------------------------------

_REGEX_SPECIALS = set(".^$*+?{}[]()|\\/")


def _lit(text: str) -> str:
    """Escape a literal string for the regex engine above."""
    return "".join(
        ("\\" + ch) if ch in _REGEX_SPECIALS else ch for ch in text
    )

# escape-free JSON string body: any printable char except '"' and '\'
_STR_BODY = '[^"\\\\]*'
_INT = "-?(0|[1-9]\\d*)"
_NUMBER = _INT + "(\\.\\d+)?([eE][-+]?\\d+)?"


def schema_to_regex(schema) -> str:
    """Lower a JSON-Schema subset to a regex over canonical compact
    JSON (no whitespace; object properties in declared order, all
    treated as required). Unsupported constructs fail typed — a
    constraint that silently under-constrains would defeat the whole
    guarantee."""
    if not isinstance(schema, dict):
        raise ConstraintCompileError(
            f"json_schema must be an object, got {type(schema).__name__}"
        )
    if "const" in schema:
        return _lit(json.dumps(schema["const"], separators=(",", ":")))
    if "enum" in schema:
        vals = schema["enum"]
        if not isinstance(vals, list) or not vals:
            raise ConstraintCompileError("enum must be a non-empty list")
        return (
            "("
            + "|".join(
                _lit(json.dumps(v, separators=(",", ":"))) for v in vals
            )
            + ")"
        )
    t = schema.get("type")
    if t == "string":
        return '"' + schema_string_body(schema) + '"'
    if t == "integer":
        return _INT
    if t == "number":
        return _NUMBER
    if t == "boolean":
        return "(true|false)"
    if t == "null":
        return "null"
    if t == "object":
        props = schema.get("properties", {})
        if not isinstance(props, dict):
            raise ConstraintCompileError("properties must be an object")
        if not props:
            return "\\{\\}"
        parts = [
            '"' + _lit(name) + '":' + schema_to_regex(sub)
            for name, sub in props.items()
        ]
        return "\\{" + ",".join(parts) + "\\}"
    if t == "array":
        items = schema.get("items")
        if items is None:
            raise ConstraintCompileError(
                "array schemas need 'items' (unbounded heterogeneous "
                "arrays are not supported)"
            )
        item = schema_to_regex(items)
        return "\\[((" + item + ")(,(" + item + "))*)?\\]"
    raise ConstraintCompileError(
        f"unsupported json_schema construct: {schema!r} (supported: "
        "const, enum, string, integer, number, boolean, null, object "
        "with properties, array with items)"
    )


def schema_string_body(schema: dict) -> str:
    """The regex for a JSON string's BODY (between the quotes):
    escape-free printable chars, optionally bounded by
    min/maxLength."""
    lo = schema.get("minLength", 0)
    hi = schema.get("maxLength")
    if hi is None and lo == 0:
        return _STR_BODY
    if hi is None:
        return '[^"\\\\]{%d,}' % lo
    return '[^"\\\\]{%d,%d}' % (lo, hi)


# ---------------------------------------------------------------------
# char DFA -> token-level FSM over a concrete vocabulary
# ---------------------------------------------------------------------


class TokenFsm:
    """The per-constraint tables the engine's hot path reads.

    ``masks[s]`` is the (V,) bool row of tokens allowed in state ``s``
    (the EOS column is set exactly on accepting states when an EOS id
    was compiled in); ``trans[s, t]`` the successor state (-1 where
    disallowed; EOS has no successor — the engine finishes on EOS
    before advancing). ``start`` is always 0. ``nbytes`` feeds the
    cache's byte accounting."""

    def __init__(self, masks: np.ndarray, trans: np.ndarray,
                 accepting: np.ndarray, eos_token_id: Optional[int]):
        self.masks = masks
        self.trans = trans
        self.accepting = accepting
        self.eos_token_id = eos_token_id
        self.start = 0
        self.n_states = int(masks.shape[0])
        self.nbytes = masks.nbytes + trans.nbytes + accepting.nbytes

    def allowed_row(self, state: int) -> np.ndarray:
        """Mask row for ``state``; all-zero for the dead-end sentinel
        (state < 0 — only the ``constrain_dead_end`` fault plants
        it)."""
        if state < 0:
            return np.zeros((self.masks.shape[1],), bool)
        return self.masks[state]

    def advance(self, state: int, token: int) -> int:
        """Successor state after emitting ``token`` (-1 when the
        token was not allowed — unreachable when the mask was applied,
        kept defensive)."""
        if state < 0:
            return -1
        return int(self.trans[state, token])

    def is_accepting(self, state: int) -> bool:
        return state >= 0 and bool(self.accepting[state])

    def walk(self, tokens: Sequence[int]) -> int:
        """Host-side multi-token advance (drafter filtering, output
        validation): returns the state after consuming ``tokens``, or
        -1 at the first disallowed one."""
        s = self.start
        for t in tokens:
            if s < 0:
                return -1
            s = int(self.trans[s, t])
        return s

    def prefix_len(self, tokens: Sequence[int],
                   state: Optional[int] = None) -> int:
        """How many leading ``tokens`` stay inside the language —
        the drafter-proposal truncation point. ``state`` starts the
        walk mid-stream (a slot's current FSM cursor); default the
        start state."""
        s = self.start if state is None else state
        for i, t in enumerate(tokens):
            nxt = int(self.trans[s, t]) if s >= 0 else -1
            if nxt < 0:
                return i
            s = nxt
        return len(tokens)

    def matches(self, tokens: Sequence[int]) -> bool:
        """Whether ``tokens`` (EOS stripped by the caller) lands on an
        accepting state — the bench's schema-validity oracle."""
        s = self.walk(tokens)
        return self.is_accepting(s)


def build_token_fsm(dfa: CharDfa, vocab: Sequence[str],
                    eos_token_id: Optional[int]) -> TokenFsm:
    """Char DFA -> token FSM (Willard & Louf 2023, their Algorithms
    3/4 in spirit): from every LIVE char state, walk each vocabulary
    token's string; tokens whose every char transition exists are
    allowed and map to the end state. Dead char states (no path to an
    accepting state) are pruned first so the token FSM cannot
    dead-end naturally; the empty language fails typed here."""
    n = len(dfa.step)
    # liveness: reverse-reachability from accepting states
    rev: List[set] = [set() for _ in range(n)]
    for s, edges in enumerate(dfa.step):
        for dst in edges.values():
            rev[dst].add(s)
    live = set(dfa.accepting)
    stack = list(live)
    while stack:
        s = stack.pop()
        for p in rev[s]:
            if p not in live:
                live.add(p)
                stack.append(p)
    if 0 not in live:
        raise ConstraintCompileError(
            "constraint matches nothing (empty language)"
        )
    # renumber live states; char start becomes token-FSM state 0
    order = [0] + sorted(s for s in live if s != 0)
    renum = {s: i for i, s in enumerate(order)}
    S, V = len(order), len(vocab)
    masks = np.zeros((S, V), bool)
    trans = np.full((S, V), -1, np.int32)
    accepting = np.zeros((S,), bool)
    for old, new in renum.items():
        if old in dfa.accepting:
            accepting[new] = True
    # token walks, memoized per (state, token) via per-state char walk
    for old, new in renum.items():
        for tid, text in enumerate(vocab):
            if not text:
                continue  # empty-string tokens can never advance
            s = old
            ok = True
            for ch in text:
                s = dfa.step[s].get(ch, -1)
                if s < 0 or s not in live:
                    ok = False
                    break
            if ok:
                masks[new, tid] = True
                trans[new, tid] = renum[s]
    if eos_token_id is not None:
        if not 0 <= eos_token_id < V:
            raise ConstraintCompileError(
                f"eos_token_id {eos_token_id} outside vocab ({V})"
            )
        masks[accepting, eos_token_id] = True
        trans[accepting, eos_token_id] = -1  # EOS ends the request
    if not masks[0].any():
        raise ConstraintCompileError(
            "vocabulary cannot spell the constraint (no token is "
            "allowed in the start state)"
        )
    return TokenFsm(masks, trans, accepting, eos_token_id)


# ---------------------------------------------------------------------
# the per-request entry point + the refcounted compile cache
# ---------------------------------------------------------------------


def spec_key(params, eos_token_id: Optional[int]) -> Optional[tuple]:
    """Canonical cache key for a request's constraint, or None when it
    is unconstrained. Exactly one of json_schema/regex/choices may be
    set (SamplingParams validates); the EOS id is part of the key
    because it lands in the masks."""
    if params.json_schema is not None:
        return ("json_schema", params.json_schema, eos_token_id)
    if params.regex is not None:
        return ("regex", params.regex, eos_token_id)
    if params.choices is not None:
        return ("choices", params.choices, eos_token_id)
    return None


def compile_constraint(key: tuple, vocab: Sequence[str]) -> TokenFsm:
    """Compile one canonical constraint key against a vocabulary.
    The ``constrain_compile_fail`` fault point fires here (call-
    counted, utils/faults.py) as a typed compile error — the injected
    stand-in for a malformed schema reaching a production submit."""
    try:
        faults.check("constrain_compile_fail")
    except faults.FaultInjected as e:
        raise ConstraintCompileError(
            f"injected constraint compile failure: {e}"
        ) from e
    kind, spec, eos = key
    if kind == "json_schema":
        try:
            schema = json.loads(spec)
        except json.JSONDecodeError as e:
            raise ConstraintCompileError(
                f"json_schema is not valid JSON: {e}"
            ) from e
        pattern = schema_to_regex(schema)
    elif kind == "regex":
        pattern = spec
    elif kind == "choices":
        pattern = "(" + "|".join(_lit(c) for c in spec) + ")"
    else:  # pragma: no cover - spec_key is the only producer
        raise ConstraintCompileError(f"unknown constraint kind {kind!r}")
    return build_token_fsm(compile_regex(pattern), vocab, eos)


class _Entry:
    __slots__ = ("fsm", "refs", "last_use")

    def __init__(self, fsm: TokenFsm, clock: int):
        self.fsm = fsm
        self.refs = 0
        self.last_use = clock


class ConstraintCache:
    """Refcounted, LRU-evicting, byte-accounted compile cache.

    The radix prefix cache's discipline applied to FSM tables: N
    concurrent requests with the same schema share ONE compile
    (refs = N); entries at refcount 0 survive as LRU cache until
    ``max_entries`` forces eviction, so a burst of identical
    tool-calling requests compiles once ever. All mutable state is
    guarded by ``self._lock`` (GL301): the engine thread acquires/
    releases while /health and /metrics readers call :meth:`stats`.
    """

    def __init__(self, max_entries: int = 32):
        if max_entries < 1:
            raise ValueError(
                f"max_entries must be >= 1, got {max_entries}"
            )
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._entries: Dict[tuple, _Entry] = {}
        self._clock = 0
        self._hits = 0
        self._misses = 0
        self._evictions = 0

    def acquire(self, key: tuple, vocab: Sequence[str]) -> TokenFsm:
        """Return the compiled FSM for ``key``, compiling on miss;
        the caller owns one reference until :meth:`release`. The
        compile itself runs OUTSIDE the lock (GL602: nothing blocking
        under it) — a racing double-compile of the same key is
        harmless and the second result wins the slot."""
        with self._lock:
            self._clock += 1
            ent = self._entries.get(key)
            if ent is not None:
                ent.refs += 1
                ent.last_use = self._clock
                self._hits += 1
                return ent.fsm
            self._misses += 1
        fsm = compile_constraint(key, vocab)
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                ent = _Entry(fsm, self._clock)
                self._entries[key] = ent
                self._evict_locked()
            ent.refs += 1
            ent.last_use = self._clock
            return ent.fsm

    def release(self, key: tuple) -> None:
        """Drop one reference; entries stay cached at refcount 0
        until LRU eviction needs the slot."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None and ent.refs > 0:
                ent.refs -= 1

    def _evict_locked(self) -> None:
        # evict oldest refcount-0 entries until within capacity;
        # referenced entries are never evicted (a slot mid-decode
        # reads its masks every step)
        while len(self._entries) > self.max_entries:
            victims = [
                (e.last_use, k) for k, e in self._entries.items()
                if e.refs == 0
            ]
            if not victims:
                return  # every entry referenced: soft cap
            _, key = min(victims)
            del self._entries[key]
            self._evictions += 1  # graftlint: threadsafe (_locked helper: every caller holds self._lock)

    def stats(self) -> dict:
        """Locked snapshot for /health and the /metrics gauges."""
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": sum(
                    e.fsm.nbytes for e in self._entries.values()
                ),
                "referenced": sum(
                    1 for e in self._entries.values() if e.refs > 0
                ),
                "hits_total": self._hits,
                "misses_total": self._misses,
                "evictions_total": self._evictions,
            }
