"""Serving request/response dataclasses.

The unit of work for the continuous-batching engine (serving/engine.py):
a token-id prompt plus per-request sampling parameters threaded through
the same ``sample_token`` contract as models/generate.py (temperature-1
categorical by default, temperature 0 = greedy, optional top-k). Each
request carries its own ``seed``: the engine derives the key for the
t-th generated token as ``fold_in(PRNGKey(seed), t)``, so sampled output
is a pure function of (params, prompt, params, seed) — independent of
slot assignment, batch composition, and admission order. Timing fields
on the output feed the serving bench's TTFT/ITL percentiles
(tools/serve_bench.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

# Priority classes, best-first. The scheduler (serving/scheduler.py)
# admits by effective rank = PRIORITY_RANK[class] - age/priority_aging_s,
# so a starved batch request eventually outranks fresh high traffic.
PRIORITY_CLASSES = ("high", "normal", "batch")
PRIORITY_RANK = {c: i for i, c in enumerate(PRIORITY_CLASSES)}


@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs (models/generate.py:sample_token).

    Defaults reproduce the reference generation contract: temperature 1,
    no top-k (control.py:168-169). ``temperature <= 0`` means greedy
    argmax; ``top_k`` None/0 means off (negative is rejected — it used
    to slip through silently and explode inside the batched sampler).
    The full field table lives in README.md ("Structured decoding").
    """

    max_new_tokens: int = 16
    temperature: float = 1.0
    top_k: Optional[int] = None
    seed: int = 0
    # Stop token for THIS request; None defers to ServingConfig's
    # engine-wide default. The matching token is included in the output.
    eos_token_id: Optional[int] = None
    # Per-request cap on speculative draft length (serving/spec.py):
    # at most this many drafted tokens are verified per iteration for
    # this request. None = the engine's ServingConfig.spec_draft_len;
    # 0 = speculation off for this request. Caps above the engine's
    # compiled draft ladder clamp to it — per-request draft lengths
    # ride the jitted verify step as runtime arrays, never recompiling.
    draft_len: Optional[int] = None
    # ---- structured decoding (serving/constrain.py) -----------------
    # At most ONE of json_schema / regex / choices may be set. Each is
    # compiled once into a token-level FSM (cached/refcounted across
    # requests) whose per-state masks ride the jitted pool step as
    # runtime arrays — constrained traffic never recompiles.
    json_schema: Optional[str] = None  # JSON text of the schema
    regex: Optional[str] = None
    choices: Optional[tuple] = None  # tuple of candidate strings
    # ---- logit pipeline ---------------------------------------------
    # repetition_penalty: >1 divides positive / multiplies negative
    # logits of already-generated tokens (1.0 = off); presence/
    # frequency subtract flat / count-proportional penalties
    # (0.0 = off). Applied BEFORE the constraint mask and top-k.
    repetition_penalty: float = 1.0
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # Multi-token stop sequences: tuple of token-id tuples. Generation
    # finishes with finish_reason="stop_sequence" when the generated
    # tail matches any sequence (match included in the output, like
    # eos). Host-side suffix check — never touches the jitted step.
    stop: Optional[tuple] = None
    # Echo per-token logprobs: 0 = off; N>0 returns the chosen token's
    # logprob plus the top-N (id, logprob) alternatives per emitted
    # token, capped by ServingConfig.max_logprobs.
    logprobs: int = 0
    # Priority class (PRIORITY_CLASSES): "high" = interactive traffic
    # the scheduler admits first and never preempts; "batch" = bulk
    # traffic that yields its pages (mid-decode preemption to the host
    # tier) when higher classes are blocked on the pool. Anti-starvation
    # aging (ServingConfig.priority_aging_s) guarantees batch progress.
    priority: str = "normal"
    # Resume-by-replay (serving/migrate.py): the request's last
    # key_offset PROMPT tokens were emitted by an earlier attempt that
    # died mid-decode. The engine offsets the fold_in key chain by it
    # (token t samples with key position key_offset + t), seeds the
    # penalty histogram and constraint-FSM cursor from that prompt
    # tail, and matches stop sequences across the prompt/generated
    # boundary — so the continuation is bit-identical to the
    # uninterrupted run. 0 = a normal request.
    key_offset: int = 0

    def __post_init__(self):
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}"
            )
        # type-check here, where every construction path (HTTP handler,
        # client kwargs, programmatic) funnels through: a non-int top_k
        # would otherwise only explode later inside the engine's batched
        # sampler — on the engine thread, wedging the whole server
        if self.top_k is not None and not isinstance(self.top_k, int):
            raise ValueError(f"top_k must be an int or None, got {self.top_k!r}")
        if self.top_k is not None and self.top_k < 0:
            raise ValueError(
                f"top_k must be >= 0 (0/None = off), got {self.top_k}"
            )
        if self.eos_token_id is not None and not isinstance(
            self.eos_token_id, int
        ):
            raise ValueError(
                f"eos_token_id must be an int or None, got {self.eos_token_id!r}"
            )
        if not isinstance(self.temperature, (int, float)):
            raise ValueError(
                f"temperature must be a number, got {self.temperature!r}"
            )
        if self.draft_len is not None and (
            not isinstance(self.draft_len, int) or self.draft_len < 0
        ):
            raise ValueError(
                f"draft_len must be a non-negative int or None, got "
                f"{self.draft_len!r}"
            )
        constraints = [
            k for k in ("json_schema", "regex", "choices")
            if getattr(self, k) is not None
        ]
        if len(constraints) > 1:
            raise ValueError(
                "at most one of json_schema/regex/choices may be set, "
                f"got {constraints}"
            )
        if self.json_schema is not None and not isinstance(
            self.json_schema, str
        ):
            raise ValueError(
                f"json_schema must be a JSON string, got "
                f"{self.json_schema!r}"
            )
        if self.regex is not None and not isinstance(self.regex, str):
            raise ValueError(f"regex must be a string, got {self.regex!r}")
        if self.choices is not None:
            # normalize list -> tuple so the frozen dataclass stays
            # hashable and the constraint-cache key is canonical
            if isinstance(self.choices, list):
                object.__setattr__(self, "choices", tuple(self.choices))
            if (
                not isinstance(self.choices, tuple)
                or not self.choices
                or not all(isinstance(c, str) and c for c in self.choices)
            ):
                raise ValueError(
                    "choices must be a non-empty sequence of non-empty "
                    f"strings, got {self.choices!r}"
                )
        for name in ("repetition_penalty", "presence_penalty",
                     "frequency_penalty"):
            v = getattr(self, name)
            if not isinstance(v, (int, float)):
                raise ValueError(f"{name} must be a number, got {v!r}")
        if self.repetition_penalty <= 0:
            raise ValueError(
                "repetition_penalty must be > 0 (1.0 = off), got "
                f"{self.repetition_penalty}"
            )
        if self.stop is not None:
            if isinstance(self.stop, list):
                object.__setattr__(
                    self, "stop",
                    tuple(tuple(int(t) for t in s) for s in self.stop),
                )
            if (
                not isinstance(self.stop, tuple)
                or not self.stop
                or not all(
                    isinstance(s, tuple) and s
                    and all(isinstance(t, int) for t in s)
                    for s in self.stop
                )
            ):
                raise ValueError(
                    "stop must be a non-empty sequence of non-empty "
                    f"token-id sequences, got {self.stop!r}"
                )
        if not isinstance(self.logprobs, int) or self.logprobs < 0:
            raise ValueError(
                f"logprobs must be a non-negative int, got "
                f"{self.logprobs!r}"
            )
        if self.priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"priority must be one of {PRIORITY_CLASSES}, got "
                f"{self.priority!r}"
            )
        if not isinstance(self.key_offset, int) or self.key_offset < 0:
            raise ValueError(
                f"key_offset must be a non-negative int, got "
                f"{self.key_offset!r}"
            )

    @property
    def constrained(self) -> bool:
        """Whether any structured-decoding constraint is set."""
        return (
            self.json_schema is not None
            or self.regex is not None
            or self.choices is not None
        )


@dataclass(frozen=True)
class Request:
    """One queued generation: a prompt (token ids) + sampling params."""

    request_id: int
    prompt: tuple  # token ids, length >= 1
    params: SamplingParams = field(default_factory=SamplingParams)

    @staticmethod
    def make(request_id: int, prompt: Sequence[int],
             params: Optional[SamplingParams] = None, **kw) -> "Request":
        """Convenience constructor: ``kw`` are SamplingParams fields."""
        if params is None:
            params = SamplingParams(**kw)
        elif kw:
            raise ValueError("pass params or keyword fields, not both")
        prompt = tuple(int(t) for t in prompt)
        if not prompt:
            raise ValueError("prompt must be non-empty")
        return Request(request_id=request_id, prompt=prompt, params=params)


@dataclass
class RequestOutput:
    """Completed generation + the timestamps the bench needs.

    ``tokens`` holds only the GENERATED ids (eos included when hit);
    ``prompt`` echoes the prompt the engine actually ran — for the RoPE
    families a longer-than-block_size prompt is cropped to its last
    block_size ids, the reference's own semantics (control.py:165,
    mirrored by generate_cached, models/decode.py).
    """

    request_id: int
    prompt: List[int]
    tokens: List[int]
    # "length" | "eos" | "stop_sequence" | "constraint_complete" |
    # "constraint_dead_end" | "deadline" | "page_exhausted"
    finish_reason: str
    submit_time: float = 0.0
    first_token_time: float = 0.0
    finish_time: float = 0.0
    # host timestamp at which each generated token was collected
    token_times: List[float] = field(default_factory=list)
    # cross-process trace id (obs/trace.py) when the request carried a
    # trace context — echoed in HTTP replies so a slow request can be
    # looked up in the stitched timeline (tools/trace_stitch.py)
    trace_id: Optional[str] = None
    # speculative-decoding accounting (serving/spec.py): draft tokens
    # the drafter proposed for this request and how many the target
    # accepted — the per-request view of the engine-wide
    # serving_spec_{proposed,accepted}_tokens_total counters. Both 0
    # when speculation was off (or never engaged) for this request.
    spec_proposed: int = 0
    spec_accepted: int = 0
    # logprob echo (params.logprobs > 0): per generated token the
    # chosen token's logprob, and the top-N (token_id, logprob)
    # alternatives — both computed on the PROCESSED logits (penalties
    # + constraint mask applied), i.e. the distribution actually
    # sampled from. None when the request did not ask for logprobs.
    token_logprobs: Optional[List[float]] = None
    top_logprobs: Optional[List[List[tuple]]] = None
    # Backoff hint for shed requests (finish_reason "page_exhausted"):
    # seconds until the pool is expected to drain enough pages, from
    # PagePool.estimated_drain_s (observed eviction/release throughput).
    # None = no estimate; HTTP Retry-After falls back to queue bounds.
    retry_after: Optional[float] = None
    # Per-request model-quality stats (obs/quality.py) when the engine
    # runs with ServingConfig.quality_telemetry: mean sampled-
    # distribution entropy and top-1 logit margin over the request's
    # FINITE per-token signals (None means every signal was "no
    # signal"), the count actually observed, the longest
    # repeat-of-previous-token run, and the spec acceptance ratio when
    # speculation engaged. None when telemetry is off.
    quality: Optional[dict] = None

    @property
    def ttft(self) -> float:
        """Time to first token (seconds)."""
        return self.first_token_time - self.submit_time

    @property
    def itls(self) -> List[float]:
        """Inter-token latencies (seconds) between consecutive tokens."""
        return [
            b - a for a, b in zip(self.token_times[:-1], self.token_times[1:])
        ]
