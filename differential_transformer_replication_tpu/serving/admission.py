"""Predictive admission: honest Retry-After from fleet-wide capacity.

The router's original shed paths answered backpressure with a STATIC
``Retry-After`` (``RouterConfig.shed_retry_after_s``) — a constant that
is honest only by accident. A saturated fleet that will take 20 s to
drain its backlog telling clients "retry in 1 s" manufactures a retry
storm; one that will recover in 200 ms telling them "retry in 30 s"
manufactures an outage. This module computes the truthful number from
two quantities the fleet already measures (the Kwon et al. 2023 stance
— admission must key on TRUE capacity, not per-replica queue bounds):

- **backlog ahead of this request** — requests already running (slot
  occupancy, fleet-wide) plus requests queued in priority classes at
  or above the new request's class (``serving_queue_depth_by_class``;
  the priority scheduler admits strictly by effective rank, so a batch
  request waits behind every queued high/normal request but a high
  request only waits behind other highs);
- **measured service rate** — an EWMA of fleet-wide request
  completions per second, read as deltas of the replicas'
  ``serving_requests_completed_total`` counters between probes
  (restart-safe: a counter that goes backwards contributes zero, not a
  negative rate).

``predicted wait = backlog_ahead / service_rate`` — per priority
class, fleet-wide. The router uses it two ways:

1. every shed (``no_replica``, exhausted failover, proactive) carries
   ``Retry-After = clamp(predicted wait)`` instead of the static
   default;
2. with ``admission_wait_bound_s > 0``, requests whose predicted wait
   exceeds their class bound (high 2x, normal 1x, batch 0.5x — batch
   sheds first, high last) are shed AT ADMISSION with that honest
   header, before they burn a failover attempt on a fleet that cannot
   serve them in time.

The controller is fed by the router's existing probe loop
(:meth:`observe_replica` with each replica's scraped ``/metrics``
body) — no new network traffic. Pure stdlib, no jax: the math
functions are module-level and the whole state machine runs on
injected clocks/expositions in tests/test_autoscaler.py.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from differential_transformer_replication_tpu.config import RouterConfig
from differential_transformer_replication_tpu.obs.registry import (
    parse_exposition,
)
from differential_transformer_replication_tpu.serving.request import (
    PRIORITY_CLASSES,
    PRIORITY_RANK,
)

# Proactive-shed bound multipliers: a class's tolerated predicted wait
# is admission_wait_bound_s * this. Batch tolerates half the base bound
# (sheds first), high twice it (sheds last) — the same ordering the
# engine's priority scheduler enforces once a request is admitted.
CLASS_WAIT_MULT = {"high": 2.0, "normal": 1.0, "batch": 0.5}


# -- the pure math (the test suite's Retry-After oracle drives these) ---


def backlog_ahead(queued_by_class: Dict[str, float], running: float,
                  priority: str) -> float:
    """Requests a NEW ``priority``-class arrival waits behind: everything
    already running plus every queued request in a class of equal or
    higher priority (lower rank). Unknown classes rank as "normal"."""
    rank = PRIORITY_RANK.get(priority, PRIORITY_RANK["normal"])
    queued = sum(
        max(0.0, count) for cls, count in queued_by_class.items()
        if PRIORITY_RANK.get(cls, PRIORITY_RANK["normal"]) <= rank
    )
    return max(0.0, running) + queued


def predicted_wait_s(backlog: float,
                     service_rate: Optional[float]) -> Optional[float]:
    """Seconds until the fleet has worked off ``backlog`` requests at
    the measured rate; None when no rate has been measured yet (no
    traffic history is not the same as infinite capacity)."""
    if service_rate is None or service_rate <= 0:
        return None
    return max(0.0, backlog) / service_rate


def honest_retry_after(wait_s: Optional[float], fallback_s: float,
                       cap_s: float) -> float:
    """The Retry-After value for a shed: the predicted wait, floored at
    1 s (the header is delta-seconds; 0 invites an instant re-pile-on)
    and capped (a deep backlog must read "come back soon and re-ask",
    not "come back in an hour"). Falls back to the static default when
    no wait could be predicted."""
    if wait_s is None:
        return max(1.0, fallback_s)
    return max(1.0, min(wait_s, cap_s))


@dataclass
class AdmissionDecision:
    """One admission ruling: ``admitted`` False means shed NOW with
    ``retry_after_s`` (honest), for ``reason``."""

    admitted: bool
    retry_after_s: float
    predicted_wait_s: Optional[float]
    reason: str = ""


class _RateEWMA:
    """EWMA of a rate sampled from an event accumulator at irregular
    intervals: alpha adapts to the gap (halflife semantics), so a
    slow probe cadence does not under-weight fresh evidence."""

    def __init__(self, halflife_s: float):
        self.halflife_s = max(1e-6, halflife_s)
        self.value: Optional[float] = None
        self._last_t: Optional[float] = None
        self._last_acc = 0.0

    def sample(self, acc: float, now: float) -> Optional[float]:
        if self._last_t is None:
            self._last_t, self._last_acc = now, acc
            return self.value
        dt = now - self._last_t
        if dt < 0.05:  # too close to measure a rate
            return self.value
        rate = max(0.0, acc - self._last_acc) / dt
        self._last_t, self._last_acc = now, acc
        alpha = 1.0 - 0.5 ** (dt / self.halflife_s)
        self.value = (
            rate if self.value is None
            else self.value + alpha * (rate - self.value)
        )
        return self.value


class AdmissionController:
    """Fleet-capacity admission state fed by the router's probe loop.

    ``observe_replica(name, exposition, now)`` ingests one replica's
    freshly scraped ``/metrics`` body (queue depths per class, slot
    occupancy, completion counter); ``retry_after_s``/``admit`` answer
    from the aggregate. All clocks are injectable and every ruling is
    derived from the pure functions above, so decisions replay
    bit-identically from recorded expositions."""

    def __init__(self, cfg: RouterConfig, registry=None,
                 now_fn: Callable[[], float] = time.monotonic):
        self.cfg = cfg
        self._now = now_fn
        self._lock = threading.Lock()
        # per-replica parsed state: name -> dict(queued_by_class,
        # running, completed_total)
        self._replicas: Dict[str, dict] = {}
        self._completed_acc = 0.0  # fleet completions, restart-safe
        self._rate = _RateEWMA(cfg.admission_rate_halflife_s)
        self._wait_gauge = None
        self._rate_gauge = None
        if registry is not None:
            self._wait_gauge = registry.gauge(
                "admission_predicted_wait_seconds",
                "Predicted wait for a NEW request of this priority "
                "class (fleet backlog ahead of it / measured service "
                "rate).",
                labelnames=("priority",),
            )
            self._rate_gauge = registry.gauge(
                "admission_service_rate",
                "Measured fleet service rate (completed requests/sec, "
                "EWMA over probe-window counter deltas).",
            )

    # -- ingest (router probe loop) ------------------------------------

    def observe_replica(self, name: str, exposition: str,
                        now: Optional[float] = None) -> None:
        """Ingest one replica's freshly scraped /metrics body."""
        now = self._now() if now is None else now
        _, samples = parse_exposition(exposition)
        queued_by_class: Dict[str, float] = {}
        queue_total = 0.0
        running = 0.0
        completed = 0.0
        for sample_name, labels, value in samples:
            if sample_name == "serving_queue_depth_by_class":
                cls = labels.get("priority", "normal")
                queued_by_class[cls] = (
                    queued_by_class.get(cls, 0.0) + value
                )
            elif sample_name == "serving_queue_depth":
                queue_total += value
            elif sample_name == "serving_slot_occupancy":
                running += value
            elif sample_name == "serving_requests_completed_total":
                completed += value
        if not queued_by_class and queue_total > 0:
            # a replica without per-class depth gauges (older build):
            # count its whole queue as "normal"
            queued_by_class["normal"] = queue_total
        with self._lock:
            prev = self._replicas.get(name)
            if prev is not None:
                # restart-safe: a counter that went backwards (replica
                # relaunch) contributes zero this window, not negative
                self._completed_acc += max(
                    0.0, completed - prev["completed_total"]
                )
            self._replicas[name] = {
                "queued_by_class": queued_by_class,
                "running": running,
                "completed_total": completed,
            }
            rate = self._rate.sample(self._completed_acc, now)
            if self._rate_gauge is not None and rate is not None:
                self._rate_gauge.set(rate)
            if self._wait_gauge is not None:
                for cls in PRIORITY_CLASSES:
                    wait = self._predicted_wait_locked(cls)
                    if wait is not None:
                        self._wait_gauge.set(wait, priority=cls)

    def forget_replica(self, name: str) -> None:
        """Drop a scaled-away/removed replica's contribution (its
        counters leave the rate accumulator's baseline too)."""
        with self._lock:
            self._replicas.pop(name, None)

    # -- the rulings ---------------------------------------------------

    def _aggregate_locked(self) -> tuple:
        queued: Dict[str, float] = {}
        running = 0.0
        for state in self._replicas.values():
            running += state["running"]
            for cls, count in state["queued_by_class"].items():
                queued[cls] = queued.get(cls, 0.0) + count
        return queued, running

    def _predicted_wait_locked(self, priority: str) -> Optional[float]:
        queued, running = self._aggregate_locked()
        return predicted_wait_s(
            backlog_ahead(queued, running, priority), self._rate.value
        )

    def service_rate(self) -> Optional[float]:
        with self._lock:
            return self._rate.value

    def predicted_wait(self, priority: str = "normal") -> Optional[float]:
        with self._lock:
            return self._predicted_wait_locked(priority)

    def retry_after_s(self, priority: str = "normal") -> float:
        """The honest Retry-After for shedding a ``priority`` request
        right now (static fallback until a rate is measured)."""
        return honest_retry_after(
            self.predicted_wait(priority),
            fallback_s=self.cfg.shed_retry_after_s,
            cap_s=self.cfg.admission_max_retry_after_s,
        )

    def admit(self, priority: str = "normal") -> AdmissionDecision:
        """Proactive ruling for one arriving request. Only sheds when
        ``admission_wait_bound_s`` is set AND the predicted wait for
        this class exceeds its bound — an unmeasured fleet admits."""
        wait = self.predicted_wait(priority)
        bound = self.cfg.admission_wait_bound_s
        if bound > 0 and wait is not None:
            limit = bound * CLASS_WAIT_MULT.get(priority, 1.0)
            if wait > limit:
                return AdmissionDecision(
                    admitted=False,
                    retry_after_s=honest_retry_after(
                        wait, self.cfg.shed_retry_after_s,
                        self.cfg.admission_max_retry_after_s,
                    ),
                    predicted_wait_s=wait,
                    reason=(
                        f"predicted wait {wait:.2f}s exceeds the "
                        f"{priority}-class bound {limit:.2f}s"
                    ),
                )
        return AdmissionDecision(
            admitted=True,
            retry_after_s=honest_retry_after(
                wait, self.cfg.shed_retry_after_s,
                self.cfg.admission_max_retry_after_s,
            ),
            predicted_wait_s=wait,
        )
