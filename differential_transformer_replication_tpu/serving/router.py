"""Health-aware multi-replica serving router.

PRs 3-5 built per-instance resilience — typed retriable errors with
Retry-After, ``/health``/``/ready``, graceful drain, ``/metrics``
gauges — but one engine crash or restart was still a full outage for
its traffic. This module composes N replicas into one fleet-level
endpoint (ROADMAP item 4):

- **Replica registry + active probing** — each replica is probed at
  ``GET /ready`` (state: up / draining / not_ready) and scored from its
  ``GET /metrics`` gauges (queue depth, slot occupancy, KV
  utilization). Failing replicas are probed on an exponential backoff
  and EJECTED after ``RouterConfig.eject_after`` consecutive transport
  failures; re-admission is slow (``readmit_after`` consecutive good
  probes) so a flapping host cannot oscillate into rotation.
- **Power-of-two-choices picking** — two random eligible replicas,
  lower load score wins. The score blends the probe-stale passive
  metrics with the router's own live in-flight count, so balance holds
  even between probes.
- **Failover on typed errors** — a retriable reply (503 queue_full /
  shutting_down / engine_crash, or an unreachable replica) is retried
  on a DIFFERENT replica under a total per-request deadline budget;
  non-recoverable codes (504 deadline, timeout, engine_failed) pass
  through untouched. Honored Retry-After values are capped
  (``retry_after_cap_s``) — another replica can usually serve NOW.
- **Hedging (optional)** — a request stuck past a p99-derived latency
  budget fires a second attempt on another replica; first reply wins.
- **Session affinity** — requests carrying ``session_id`` stick to one
  replica (prefix-cache locality groundwork, ROADMAP item 1) and
  re-pin elsewhere when the pinned replica dies.
- **Router-level degradation** — zero eligible replicas means a fast
  503 ``no_replica`` with Retry-After, not a hang; the router's own
  ``/health``, ``/ready`` and ``/metrics`` (per-replica request/error/
  ejection counters, pick latency, hedge counters via obs/registry.py)
  make the fleet observable as one unit.
- **Request tracing (ISSUE 7)** — every request gets a trace context
  (client ``traceparent`` or minted here, obs/trace.py), re-injected
  per attempt so each replica's spans parent to the exact forward hop
  that caused them; the router's own ``pick``/``forward``/``retry``/
  ``hedge`` spans (``--trace-path``) stitch with replica traces into
  one timeline via tools/trace_stitch.py, and every reply — success
  or failure — echoes ``trace_id``.
- **Fleet aggregation** — ``GET /fleet/metrics`` re-serves the
  replicas' last-probed ``/metrics`` bodies as ONE exposition
  (counters/histograms summed, gauges labeled per replica,
  :func:`aggregate_fleet_metrics`) plus the router's own registry and
  per-replica up/down gauges: one scrape target for the whole fleet,
  and the natural input for ``tools/slo_report.py --url``.
- **Structured events** — ejection/re-admission and request
  finished/failed/retried/hedged/shed land in a JSONL event log
  (``--event-log``, obs/events.py), each request event carrying its
  ``trace_id``.

Drain-aware by construction: a replica answering ``/ready`` 503 with
status ``draining`` (what SIGTERM triggers, serving/server.py) is
removed from rotation WITHOUT being ejected — no connection ever
breaks, which is what makes tools/fleet.py's rolling restarts
zero-loss.

Pure stdlib, no jax import — the router must keep routing while the
device runtimes it fronts are the things crashing. Successful replies
gain ``replica`` / ``attempts`` / ``hedged`` fields so every response
is attributable (tools/serve_bench.py's per-replica breakdown keys off
them).

Run standalone::

    python -m differential_transformer_replication_tpu.serving.router \
        --target http://127.0.0.1:8101 --target http://127.0.0.1:8102 \
        --port 8000

or let ``tools/fleet.py`` launch replicas + router together.
"""

from __future__ import annotations

import json
import random
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from differential_transformer_replication_tpu.config import RouterConfig
from differential_transformer_replication_tpu.obs.events import (
    NOOP_EVENTS,
)
from differential_transformer_replication_tpu.obs.registry import (
    CONTENT_TYPE as METRICS_CONTENT_TYPE,
    Registry,
    _escape_label_value,
    _fmt_value,
    parse_exposition,
    set_build_info,
)
from differential_transformer_replication_tpu.obs.spans import NOOP_TRACER
from differential_transformer_replication_tpu.obs.trace import (
    child_span_args,
    from_payload as trace_from_payload,
    instant_args,
)
from differential_transformer_replication_tpu.serving.admission import (
    AdmissionController,
)
from differential_transformer_replication_tpu.serving.migrate import (
    ReplayJournal,
)
from differential_transformer_replication_tpu.serving.retry import (
    backoff_delay,
    http_post_json_with_retries,
)
from differential_transformer_replication_tpu.utils import faults

# Replica lifecycle states. UP is the only state the picker considers.
UP = "up"                  # last probe: reachable and ready
NOT_READY = "not_ready"    # reachable, /ready 503 (e.g. restarting)
DRAINING = "draining"      # reachable, /ready 503 with status=draining
EJECTED = "ejected"        # eject_after consecutive transport failures
UNKNOWN = "unknown"        # never successfully probed yet

# Reply codes the router retries on a different replica. Anything else
# on a 503 that is not explicitly non-retriable (unknown proxies) is
# also retried — mirrors serving/retry.py's stance.
NON_RETRIABLE_503_CODES = ("timeout", "engine_failed")

# /metrics gauge names (serving/engine.py) -> Replica score fields.
_SCORE_METRICS = {
    "serving_queue_depth": "queue_depth",
    "serving_slot_occupancy": "slot_occupancy",
    "serving_slots": "slots",
    "serving_kv_utilization": "kv_utilization",
}


def parse_replica_scores(text: str) -> Dict[str, float]:
    """Extract the load-score gauges from a Prometheus text exposition.
    Unknown/malformed lines are skipped — a replica with a bigger
    registry (or none of these gauges) still probes fine."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) != 2:
            continue
        key = _SCORE_METRICS.get(parts[0])
        if key is not None:
            try:
                out[key] = float(parts[1])
            except ValueError:
                pass
    return out


def _histogram_base(name: str, types: Dict[str, str]) -> Optional[str]:
    """The histogram family a ``*_bucket``/``*_sum``/``*_count`` sample
    belongs to, or None for a plain sample name."""
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if types.get(base) == "histogram":
                return base
    return None


def aggregate_fleet_metrics(bodies: Dict[str, str],
                            own: str = "") -> str:
    """Merge N replicas' ``/metrics`` bodies (plus the router's
    ``own``) into ONE fleet exposition — the single scrape target
    ``GET /fleet/metrics`` serves:

    - **counters and histograms are summed** across replicas by
      identical label set (histogram buckets are cumulative counters,
      so per-``le`` sums stay a valid histogram) — fleet throughput is
      the sum of replica throughputs;
    - **gauges keep per-replica identity**: each sample gains a
      ``replica="host:port"`` label (summing slot occupancies would
      hide exactly the imbalance a fleet scrape exists to show), which
      also keeps per-replica ``build_info``/``process_start_time``
      distinguishable — and is exactly what the model-quality plane
      rides: ``serving_quality_drift`` / ``serving_lambda_mean`` /
      ``serving_constraint_validity_rate`` (obs/quality.py) arrive
      per-replica with zero router changes, so the canary judge
      (tools/autoscaler.py) reads one arm's drift without the control
      arm diluting it;
    - the router's ``own`` metrics pass through unmodified, merged
      under the same TYPE declarations so shared names (``build_info``)
      render once.

    Unknown or malformed samples are skipped; replicas with disjoint
    metric sets union cleanly. Pure function — tests drive it with
    canned bodies and the oracle exposition parser."""
    kinds: Dict[str, str] = {}
    # sample name -> ordered {(label tuple) -> value}; summed flag per
    # name decides merge semantics
    values: "OrderedDict[str, OrderedDict]" = OrderedDict()

    def _add(sample_name: str, labels: Dict[str, str], value: float,
             summed: bool) -> None:
        per = values.setdefault(sample_name, OrderedDict())
        key = tuple(sorted(labels.items()))
        if summed and key in per:
            per[key] += value
        else:
            per[key] = value

    def _ingest(text: str, replica: Optional[str]) -> None:
        types, samples = parse_exposition(text)
        for name, kind in types.items():
            kinds.setdefault(name, kind)
        for sample_name, labels, value in samples:
            base = _histogram_base(sample_name, types)
            family = base or sample_name
            kind = types.get(family, "untyped")
            if replica is None:
                _add(sample_name, labels, value, summed=False)
            elif kind in ("counter", "histogram"):
                _add(sample_name, labels, value, summed=True)
            else:  # gauge/untyped: keep replica identity
                _add(sample_name, {**labels, "replica": replica},
                     value, summed=False)

    if own:
        _ingest(own, None)
    for replica_name, text in bodies.items():
        _ingest(text, replica_name)

    out: List[str] = []
    seen_types = set()
    for sample_name in sorted(values):
        base = _histogram_base(sample_name, kinds)
        family = base or sample_name
        if family not in seen_types:
            seen_types.add(family)
            out.append(
                f"# TYPE {family} {kinds.get(family, 'untyped')}"
            )
        for key, value in values[sample_name].items():
            lbl = (
                "{" + ",".join(
                    f'{k}="{_escape_label_value(v)}"' for k, v in key
                ) + "}"
                if key else ""
            )
            out.append(f"{sample_name}{lbl} {_fmt_value(value)}")
    return "\n".join(out) + "\n" if out else ""


class Replica:
    """One backend's registry entry: URL, health state machine, passive
    load scores, and router-side in-flight count. All mutation happens
    under ``self.lock``; the state machine itself is pure bookkeeping
    (``note_*`` methods) so tests drive it without HTTP."""

    def __init__(self, url: str, cfg: RouterConfig):
        self.url = url.rstrip("/")
        # label/attribution name: host:port reads better than a full URL
        split = urllib.parse.urlsplit(self.url)
        self.name = split.netloc or self.url
        self.cfg = cfg
        self.lock = threading.Lock()
        self.state = UNKNOWN
        self.status = "unknown"    # replica-reported status string
        self.consec_fail = 0       # consecutive transport failures
        self.consec_ok = 0         # consecutive good probes (re-admission)
        self.ejections = 0
        self.inflight = 0          # router-side live requests
        self.queue_depth = 0.0
        self.slot_occupancy = 0.0
        self.slots = 1.0
        self.kv_utilization = 0.0
        self.next_probe_t = 0.0    # monotonic ts of the next due probe
        self.probe_backoff = cfg.probe_backoff_s
        self.probing = False       # an async probe is in flight
        self.last_probe_ok_t: Optional[float] = None
        # last successfully fetched /metrics body (text exposition) —
        # what GET /fleet/metrics aggregates; kept across not-ready
        # windows so a draining replica's counters stay visible —
        # plus the monotonic stamp of WHEN it was fetched: the fleet
        # aggregation excludes bodies older than
        # RouterConfig.metrics_max_age_s and publishes every age as a
        # fleet_scrape_age_seconds gauge (None = never fetched by the
        # prober; a body injected without a stamp aggregates as legacy)
        self.metrics_text: str = ""
        self.metrics_t: Optional[float] = None

    def eligible(self) -> bool:
        with self.lock:
            return self.state == UP

    def score(self) -> float:
        """Load score for power-of-two-choices (lower = less loaded)."""
        cfg = self.cfg
        with self.lock:
            slots = max(1.0, self.slots)
            return (
                cfg.queue_weight * self.queue_depth / slots
                + cfg.slot_weight * self.slot_occupancy / slots
                + cfg.kv_weight * self.kv_utilization
                + self.inflight / slots
            )

    # -- health state machine -----------------------------------------

    def note_probe_success(self, ready: bool, status: str,
                           scores: Dict[str, float], now: float) -> None:
        """A probe REACHED the replica (whatever it answered)."""
        with self.lock:
            self.consec_fail = 0
            self.probe_backoff = self.cfg.probe_backoff_s
            self.next_probe_t = now + self.cfg.probe_interval_s
            self.last_probe_ok_t = now
            self.status = status
            for key, value in scores.items():
                setattr(self, key, value)
            if not ready:
                # reachable but refusing traffic: connection-free
                # removal (drain / restart), NOT an ejection — and it
                # resets the re-admission streak. An EJECTED replica
                # STAYS ejected (a booting relaunch answering
                # "restarting" must not launder away the slow
                # re-admission requirement)
                self.consec_ok = 0
                if self.state != EJECTED:
                    self.state = (
                        DRAINING if status == "draining" else NOT_READY
                    )
                return
            self.consec_ok += 1
            if self.state == EJECTED:
                # slow re-admission: one good probe is not enough
                if self.consec_ok >= self.cfg.readmit_after:
                    self.state = UP
                return
            self.state = UP

    def note_failure(self, now: float) -> bool:
        """A probe or forwarded request could not reach the replica.
        Returns True when this failure newly ejected it."""
        with self.lock:
            self.consec_ok = 0
            self.consec_fail += 1
            self.next_probe_t = now + self.probe_backoff
            self.probe_backoff = min(
                self.probe_backoff * 2, self.cfg.probe_backoff_max_s
            )
            if (self.consec_fail >= self.cfg.eject_after
                    and self.state != EJECTED):
                self.state = EJECTED
                self.ejections += 1
                return True
            return False

    def note_request_success(self) -> None:
        """A forwarded request got an HTTP answer: the transport works,
        whatever the status code said. Does NOT touch probe state —
        only probes can re-admit an ejected replica (slow re-admission
        stays meaningful under live traffic)."""
        with self.lock:
            if self.state != EJECTED:
                self.consec_fail = 0

    def snapshot(self) -> dict:
        """Point-in-time view for the router's /health JSON."""
        with self.lock:
            return {
                "url": self.url,
                "name": self.name,
                "state": self.state,
                "status": self.status,
                "inflight": self.inflight,
                "consec_fail": self.consec_fail,
                "ejections": self.ejections,
                "queue_depth": self.queue_depth,
                "slot_occupancy": self.slot_occupancy,
                "slots": self.slots,
                "kv_utilization": self.kv_utilization,
            }


class Router:
    """The fleet front: replica registry + prober + picker + failover.

    ``start()`` runs one synchronous probe pass (so the router knows its
    fleet before the first request) and then probes from a background
    thread; ``close()`` stops it. ``handle_generate`` is the whole
    request path — :func:`serve_router` is just HTTP plumbing around
    it. ``probe_fn``/``forward_fn``/``sleep``/``rng`` are injectable
    for tests.
    """

    def __init__(self, targets: Sequence[str],
                 cfg: Optional[RouterConfig] = None,
                 registry: Optional[Registry] = None,
                 rng: Optional[random.Random] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 tracer=None, events=None):
        if not targets:
            raise ValueError("router needs at least one replica URL")
        self.cfg = cfg or RouterConfig()
        self.replicas = [Replica(t, self.cfg) for t in targets]
        if len({r.url for r in self.replicas}) != len(self.replicas):
            raise ValueError(f"duplicate replica URLs in {list(targets)}")
        self.registry = registry or Registry()
        # cross-process observability (ISSUE 7): span tracer for
        # pick/forward/retry/hedge (obs/spans.py; stitchable with the
        # replicas' traces via tools/trace_stitch.py) and a structured
        # JSONL event log (obs/events.py). Both default to no-ops.
        self.tracer = tracer or NOOP_TRACER
        self.events = events or NOOP_EVENTS
        set_build_info(self.registry, role="router")
        self._rng = rng or random.Random()
        self._rng_lock = threading.Lock()
        self._sleep = sleep
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        # session affinity: session_id -> Replica, LRU-capped at
        # cfg.affinity_max_sessions (unique sessions are unbounded over
        # a router's lifetime; pins are cheap to lose)
        self._affinity: "OrderedDict[str, Replica]" = OrderedDict()
        self._aff_lock = threading.Lock()
        # latency reservoir feeding the p99-derived hedge budget
        self._lat_lock = threading.Lock()
        self._latencies: deque = deque(maxlen=512)
        # fleet membership changes (autoscaling, tools/autoscaler.py)
        # serialize through this lock; readers see atomic whole-list
        # replacement, never an in-place mutation
        self._replicas_lock = threading.Lock()
        # canaried rollout: at most one designated canary replica takes
        # a fixed fraction of non-sticky traffic (set_canary)
        self._canary_lock = threading.Lock()
        self._canary_url: Optional[str] = None
        self._canary_fraction = 0.0
        # predictive admission (serving/admission.py): honest
        # Retry-After from fleet capacity + measured service rate, fed
        # by the probe loop's /metrics scrapes
        self.admission: Optional[AdmissionController] = (
            AdmissionController(self.cfg, registry=self.registry)
            if self.cfg.admission_predictive else None
        )
        # resume-by-replay (serving/migrate.py): bounded per-inflight
        # journal of emitted tokens, harvested from each replica's
        # GET /inflight by the probe loop; on a retriable replica death
        # the request replays prompt+journal on a peer bit-exactly
        self.journal = ReplayJournal(
            max_tokens=self.cfg.replay_journal_max_tokens,
            max_finished=self.cfg.replay_journal_max_finished,
        )

        reg = self.registry
        self._req_counter = reg.counter(
            "router_requests_total",
            "Requests forwarded to a replica (attempts, incl. hedges).",
            labelnames=("replica",),
        )
        self._err_counter = reg.counter(
            "router_replica_errors_total",
            "Non-200 replica replies and transport failures, by code.",
            labelnames=("replica", "code"),
        )
        self._retry_counter = reg.counter(
            "router_retries_total",
            "Failovers: attempts re-sent to a different replica.",
        )
        self._hedge_counter = reg.counter(
            "router_hedges_total",
            "Hedged second attempts fired for slow requests.",
        )
        self._hedge_win_counter = reg.counter(
            "router_hedge_wins_total",
            "Requests whose winning reply came from the hedge.",
        )
        self._eject_counter = reg.counter(
            "router_ejections_total",
            "Replica ejections after consecutive transport failures.",
            labelnames=("replica",),
        )
        self._shed_counter = reg.counter(
            "router_shed_total",
            "Requests shed at the router (no eligible replica).",
        )
        self._admission_shed_counter = reg.counter(
            "router_admission_shed_total",
            "Requests shed proactively by predictive admission "
            "(predicted wait past the class bound), by priority.",
            labelnames=("priority",),
        )
        self._move_counter = reg.counter(
            "router_session_moves_total",
            "Sticky sessions re-pinned because their replica died.",
        )
        self._migration_counter = reg.counter(
            "router_migrations_total",
            "Fallback-ladder rungs taken for in-flight failover, by "
            "outcome: migrated (live state moved to a peer), replayed "
            "(prompt+journal resubmitted bit-exactly), migrate_failed "
            "(a migration rung failed and the ladder fell through).",
            labelnames=("outcome",),
        )
        self._journal_bytes_gauge = reg.gauge(
            "router_replay_journal_bytes",
            "Bytes of emitted tokens held in the replay journal.",
        )
        self._drain_hist = reg.histogram(
            "router_drain_seconds",
            "Wall-clock of one replica drain via live migration "
            "(migrate_out) — independent of max_new_tokens by design.",
        )
        self._pick_hist = reg.histogram(
            "router_pick_seconds",
            "Latency of one replica pick (registry scan + scoring).",
        )
        self._eligible_gauge = reg.gauge(
            "router_replicas_eligible",
            "Replicas currently in rotation (state=up).",
        )
        self._replicas_gauge = reg.gauge(
            "router_replicas", "Configured replica count."
        )
        self._replicas_gauge.set(len(self.replicas))

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "Router":
        """Probe every replica once (concurrently — one slow replica
        must not delay knowing about the others), then keep probing
        from a daemon thread."""
        now = time.monotonic()
        initial = [
            threading.Thread(target=self.probe, args=(r, now),
                             daemon=True)
            for r in self.replicas
        ]
        for t in initial:
            t.start()
        for t in initial:
            t.join(self.cfg.probe_timeout_s * 2 + 1.0)
        # start()/close() are owner-lifecycle calls (single-threaded by
        # contract); _probe_thread is never touched from request paths
        self._probe_thread = threading.Thread(  # graftlint: threadsafe (lifecycle)
            target=self._probe_loop, name="router-prober", daemon=True
        )
        self._probe_thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(5.0)
            self._probe_thread = None  # graftlint: threadsafe (lifecycle)
        # land buffered telemetry; closing is the creator's call (the
        # CLI closes in its finally, atexit is the safety net)
        self.tracer.flush()
        self.events.flush()

    # -- fleet membership (autoscaling, tools/autoscaler.py) -----------

    def add_replica(self, url: str) -> "Replica":
        """Register a new replica (scale-up) and probe it immediately.
        The whole list is REPLACED atomically, so pickers and the
        probe loop racing this call see either the old or the new
        fleet, never a half-built one."""
        with self._replicas_lock:
            current = self.replicas
            if any(r.url == url.rstrip("/") for r in current):
                raise ValueError(f"replica {url} already registered")
            replica = Replica(url, self.cfg)
            self.replicas = current + [replica]
            self._replicas_gauge.set(len(self.replicas))
        self.events.emit("replica_added", replica=replica.name)
        self.probe(replica)
        return replica

    def remove_replica(self, url: str) -> Optional["Replica"]:
        """Deregister a replica (scale-down, AFTER its drain): drops
        it from rotation, from the canary designation, and from the
        admission controller's capacity model; its affinity pins
        re-pin on the next request. Returns the removed entry (None
        when the URL was never registered)."""
        url = url.rstrip("/")
        with self._replicas_lock:
            current = self.replicas
            removed = next((r for r in current if r.url == url), None)
            if removed is None:
                return None
            if len(current) == 1:
                raise ValueError(
                    "cannot remove the last replica from the router"
                )
            self.replicas = [r for r in current if r.url != url]
            self._replicas_gauge.set(len(self.replicas))
        with self._canary_lock:
            if self._canary_url == url:
                self._canary_url = None
                self._canary_fraction = 0.0
        with self._aff_lock:
            stale = [
                sid for sid, rep in self._affinity.items()
                if rep is removed
            ]
            for sid in stale:
                del self._affinity[sid]
        if self.admission is not None:
            self.admission.forget_replica(removed.name)
        self.events.emit("replica_removed", replica=removed.name)
        self.eligible_count()
        return removed

    def set_canary(self, url: Optional[str],
                   fraction: float = 0.0) -> None:
        """Designate (or clear, url=None) the canary replica: it
        receives ``fraction`` of non-sticky picks and is EXCLUDED from
        the ordinary p2c pool and from new affinity pins, so its
        traffic share is the configured fraction, not fraction + its
        p2c share. Sticky sessions already pinned to it keep their
        pin (prefix locality); failover may still land on it when
        nothing else is eligible (serving beats shedding)."""
        if url is not None:
            url = url.rstrip("/")
            if not any(r.url == url for r in self.replicas):
                raise ValueError(f"unknown canary url {url}")
            if not 0.0 < fraction < 1.0:
                raise ValueError(
                    f"canary fraction must be in (0, 1), got {fraction}"
                )
        with self._canary_lock:
            self._canary_url = url
            self._canary_fraction = fraction if url is not None else 0.0
        self.events.emit(
            "canary_traffic_split",
            canary=url, fraction=fraction if url is not None else 0.0,
        )

    def canary(self) -> Tuple[Optional[str], float]:
        with self._canary_lock:
            return self._canary_url, self._canary_fraction

    # -- probing -------------------------------------------------------

    def _http_get(self, url: str, timeout: float) -> Tuple[int, bytes]:
        """GET returning (status, body) — reachable 503s are ANSWERS
        here, not exceptions; transport errors propagate."""
        try:
            with urllib.request.urlopen(url, timeout=timeout) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read() or b""

    def probe(self, replica: Replica, now: Optional[float] = None) -> None:
        """One probe: /ready for state, /metrics (best-effort) for load
        scores AND the raw exposition body the fleet aggregation
        re-serves. Transport failures drive the ejection state machine;
        ejection and (slow) re-admission land structured events."""
        t = self.cfg.probe_timeout_s
        prev_state = replica.state
        try:
            faults.check("router_probe_fail")
            status_code, body = self._http_get(
                replica.url + "/ready", timeout=t
            )
            try:
                payload = json.loads(body or b"{}")
            except ValueError:
                payload = {}
            ready = status_code == 200 and bool(payload.get("ready", True))
            status = str(payload.get("status", "unknown"))
            scores: Dict[str, float] = {}
            if ready:
                try:
                    code, text = self._http_get(
                        replica.url + "/metrics", timeout=t
                    )
                    if code == 200 and not faults.consume(
                        "router_stale_metrics"
                    ):
                        # (the fault point models a prober that stops
                        # refreshing: body, stamp AND scores all stay
                        # frozen at their last values — scrape-age
                        # stamping is what must surface it)
                        decoded = text.decode("utf-8", "replace")
                        scores = parse_replica_scores(decoded)
                        with replica.lock:
                            replica.metrics_text = decoded
                            replica.metrics_t = (
                                time.monotonic() if now is None else now
                            )
                        if self.admission is not None:
                            self.admission.observe_replica(
                                replica.name, decoded
                            )
                except OSError:
                    pass  # scores are advisory; /ready is the contract
                try:
                    # replay-journal harvest: each in-flight request's
                    # emitted-token prefix. Best-effort and lag-safe —
                    # a stale prefix only means a few tokens get
                    # re-generated bit-exactly on replay
                    code, text = self._http_get(
                        replica.url + "/inflight", timeout=t
                    )
                    if code == 200:
                        for ent in json.loads(
                            text or b"{}"
                        ).get("inflight", []):
                            jid = ent.get("journal_id")
                            if jid:
                                self.journal.update(
                                    str(jid),
                                    [int(x)
                                     for x in ent.get("tokens") or []],
                                )
                        self._journal_bytes_gauge.set(
                            self.journal.stats()["bytes"]
                        )
                except (OSError, ValueError):
                    pass  # pre-migration replicas have no /inflight
            replica.note_probe_success(
                ready, status, scores,
                now=time.monotonic() if now is None else now,
            )
            if prev_state == EJECTED and replica.state == UP:
                self.events.emit("replica_readmitted",
                                 replica=replica.name)
        except Exception:
            # unreachable (or an injected probe failure): one strike
            newly_ejected = replica.note_failure(
                time.monotonic() if now is None else now
            )
            if newly_ejected:
                self._eject_counter.inc(replica=replica.name)
                self.events.emit("replica_ejected", replica=replica.name,
                                 consec_fail=replica.consec_fail,
                                 via="probe")
                print(f"[router] replica {replica.name} ejected after "
                      f"{replica.consec_fail} consecutive failures",
                      file=sys.stderr)
        self.eligible_count()  # refresh the eligibility gauge

    def _probe_and_release(self, replica: Replica) -> None:
        try:
            self.probe(replica)
        finally:
            with replica.lock:
                replica.probing = False

    def _probe_loop(self) -> None:
        """Dispatch due probes, each on its own short-lived thread — a
        blackholed replica blocking its full probe timeout must not
        stall health detection (ejection, re-admission) for the rest
        of the fleet. At most one probe per replica is in flight."""
        while not self._stop.is_set():
            now = time.monotonic()
            next_due = now + self.cfg.probe_interval_s
            for r in self.replicas:
                with r.lock:
                    due = r.next_probe_t <= now and not r.probing
                    if due:
                        r.probing = True
                    elif not r.probing:
                        next_due = min(next_due, r.next_probe_t)
                if due:
                    threading.Thread(
                        target=self._probe_and_release, args=(r,),
                        daemon=True,
                    ).start()
            # wake for the earliest due probe; floor keeps a busy loop
            # impossible, cap keeps shutdown and new faults responsive
            self._stop.wait(min(max(next_due - time.monotonic(), 0.01),
                                0.25))

    # -- picking -------------------------------------------------------

    def pick(self, session_id: Optional[str] = None,
             exclude: Sequence[str] = ()) -> Optional[Replica]:
        """Choose a replica: sticky session first (if its pin is still
        eligible), else power-of-two-choices by load score. Returns
        None when nothing is eligible. ``exclude`` lists replica URLs
        already tried by this request (failover must move)."""
        t0 = time.perf_counter()
        try:
            faults.check("router_pick_raise")
            eligible = [
                r for r in self.replicas
                if r.eligible() and r.url not in exclude
            ]
            # canary split: the canary is EXCLUDED from the ordinary
            # pool (its share is exactly the configured fraction, not
            # fraction + a p2c share) unless it is the only eligible
            # replica — serving beats shedding
            canary_url, canary_frac = self.canary()
            canary = None
            pool = eligible
            if canary_url is not None:
                canary = next(
                    (r for r in eligible if r.url == canary_url), None
                )
                if canary is not None:
                    rest = [r for r in eligible if r.url != canary_url]
                    if rest:
                        pool = rest
            if session_id is not None and self.cfg.affinity:
                with self._aff_lock:
                    pinned = self._affinity.get(session_id)
                    if pinned is not None:
                        self._affinity.move_to_end(session_id)
                pinned_alive = pinned is not None and pinned.eligible()
                if pinned_alive and pinned.url not in exclude:
                    return pinned
                if not eligible:
                    return None
                # new pins come from the non-canary pool: a canary must
                # not accrete sticky sessions it keeps after promotion
                # judgment ends (or drags through rollback)
                choice = self._p2c(pool)
                if pinned_alive:
                    # the pin is healthy but excluded by THIS request's
                    # failover (a transient queue_full, say): serve
                    # elsewhere without re-pinning — one backpressure
                    # blip must not permanently forfeit the session's
                    # prefix-cache locality
                    return choice
                with self._aff_lock:
                    self._affinity[session_id] = choice
                    self._affinity.move_to_end(session_id)
                    while (len(self._affinity)
                           > self.cfg.affinity_max_sessions):
                        self._affinity.popitem(last=False)
                if pinned is not None:
                    self._move_counter.inc()  # pinned replica died
                return choice
            if not eligible:
                return None
            if canary is not None and pool is not eligible:
                with self._rng_lock:
                    roll = self._rng.random()
                if roll < canary_frac:
                    return canary
            return self._p2c(pool)
        finally:
            self._pick_hist.observe(time.perf_counter() - t0)

    def _p2c(self, eligible: List[Replica]) -> Replica:
        if len(eligible) == 1:
            return eligible[0]
        with self._rng_lock:
            a, b = self._rng.sample(eligible, 2)
        return a if a.score() <= b.score() else b

    # -- live migration / resume-by-replay (serving/migrate.py) --------

    def repin(self, session_id: str, url: str) -> bool:
        """Immediately re-pin a sticky session to the replica at
        ``url``. Before migration the affinity map only re-pinned when
        the pinned replica DIED; a migrated session's prefix-cache
        locality now lives at the destination, so the pin must follow
        the moved state right away — not after another failure."""
        url = url.rstrip("/")
        rep = next((r for r in self.replicas if r.url == url), None)
        if rep is None:
            return False
        with self._aff_lock:
            if self._affinity.get(session_id) is rep:
                self._affinity.move_to_end(session_id)
                return True
            self._affinity[session_id] = rep
            self._affinity.move_to_end(session_id)
            while len(self._affinity) > self.cfg.affinity_max_sessions:
                self._affinity.popitem(last=False)
        self._move_counter.inc()
        self.events.emit("session_repinned", session_id=session_id,
                         replica=rep.name, via="migration")
        return True

    def _await_migrated(self, dest_url: str, migrate_id: str,
                        timeout: float, ctx=None) -> Tuple[int, dict]:
        """Pick up a migrated continuation at the destination replica:
        POST /migrate/await blocks until the imported request finishes
        and answers in the exact /generate reply shape (COMPLETE token
        list — no stitching needed)."""
        payload: dict = {"migrate_id": migrate_id, "timeout": timeout}
        if ctx is not None:
            payload["traceparent"] = ctx.child().to_traceparent()
        try:
            req = urllib.request.Request(
                dest_url + "/migrate/await",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=timeout + 5.0) as r:
                body = json.load(r)
                if not isinstance(body, dict):
                    raise ValueError(f"non-object reply: {body!r}")
                return r.status, body
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read() or b"{}")
            except (ValueError, OSError):
                return e.code, {}
        except (urllib.error.URLError, TimeoutError, ConnectionError,
                OSError, ValueError) as e:
            return -1, {
                "error": f"migrated continuation at {dest_url} "
                         f"unreachable: {e!r}",
                "code": "replica_unreachable",
            }

    @staticmethod
    def _replay_finish_reason(tokens: List[int], payload: dict,
                              remaining: int) -> Optional[str]:
        """Whether the journaled prefix ALREADY completes the request
        (the source died between finishing and replying) — replaying a
        finished generation would decode extra tokens past the stop."""
        if remaining <= 0:
            return "length"
        eos = payload.get("eos_token_id")
        if eos is not None and tokens and tokens[-1] == int(eos):
            return "eos"
        for seq in payload.get("stop") or ():
            seq = [int(tok) for tok in seq]
            if seq and tokens[-len(seq):] == seq:
                return "stop_sequence"
        return None

    def migrate_out(self, url: str) -> dict:
        """Drain a replica by MIGRATING its in-flight requests to the
        least-loaded eligible peer (the tentpole of zero-loss rolling
        restarts: drain time becomes the page-transfer time, not
        max_new_tokens' worth of decoding). Enumerates the source's
        ``GET /inflight`` and POSTs ``/migrate/export`` per request;
        each successful export flips that request's blocked /generate
        into the ``migrated`` reply, which :meth:`handle_generate`
        follows to the destination. Failed exports are counted and left
        to the replay rung — the request is never harmed. Returns the
        per-outcome counts plus ``drain_seconds`` (also observed into
        ``router_drain_seconds``)."""
        url = url.rstrip("/")
        t0 = time.monotonic()
        counts = {"migrated": 0, "finished": 0, "failed": 0}
        budget = self.cfg.migrate_budget_s
        if budget <= 0:
            return {**counts, "drain_seconds": 0.0,
                    "outcome": "migration_disabled"}
        try:
            code, text = self._http_get(
                url + "/inflight", timeout=self.cfg.probe_timeout_s
            )
            entries = (
                json.loads(text or b"{}").get("inflight", [])
                if code == 200 else []
            )
        except (OSError, ValueError):
            entries = []
        for ent in entries:
            rid = ent.get("request_id")
            if rid is None:
                continue
            if not ent.get("tokens"):
                # queued / still prefilling: nothing device-side worth
                # shipping — the replay rung resubmits it wholesale
                # when the source drains
                continue
            peers = [
                r for r in self.replicas
                if r.url != url and r.eligible()
            ]
            if not peers:
                counts["failed"] += 1
                continue
            dest = min(peers, key=lambda r: r.score())
            migrate_id = uuid.uuid4().hex
            try:
                status, body, _ = http_post_json_with_retries(
                    url + "/migrate/export",
                    {"request_id": int(rid), "dest": dest.url,
                     "migrate_id": migrate_id, "budget_s": budget},
                    timeout=budget + 10.0, max_retries=0,
                    deadline_s=budget + 10.0,
                )
            except Exception:
                status, body = -1, {}
            if status == 200 and body.get("outcome") == "migrated":
                counts["migrated"] += 1
            elif status == 200:
                counts["finished"] += 1  # completed before the export
            else:
                counts["failed"] += 1
                self.events.emit(
                    "migrate_export_failed", replica=url,
                    request_id=rid,
                    code=(body or {}).get("code"),
                )
        dt = time.monotonic() - t0
        self._drain_hist.observe(dt)
        self.events.emit("replica_drained", replica=url,
                         drain_seconds=round(dt, 3), **counts)
        return {**counts, "drain_seconds": dt}

    # -- forwarding ----------------------------------------------------

    def _forward(self, replica: Replica, payload: dict, timeout: float,
                 timeout_is_deadline: bool = False, ctx=None,
                 ) -> Tuple[int, dict, Optional[float]]:
        """POST one attempt to one replica. Returns ``(status, body,
        retry_after)``; transport failures come back as status ``-1``
        with a typed body (and count toward the replica's ejection
        streak) instead of raising — the failover loop treats them like
        a retriable 503 from a replica that told us nothing.

        ``ctx`` is the request's TraceContext: each attempt derives a
        child hop, injects it as the outgoing ``traceparent`` (the
        replica's spans parent to THIS attempt, so a retried request's
        two attempts stay distinguishable in the stitched timeline),
        and wraps the attempt in a ``forward`` span.

        ``timeout_is_deadline`` marks a timeout clamped to the
        request's remaining deadline budget: hitting it means the
        REQUEST ran out of time while the replica worked, so it maps
        to a non-retriable 504 ``deadline`` and the replica takes no
        ejection strike — three slow requests must not eject a healthy
        replica."""
        span_args = {"replica": replica.name}
        if ctx is not None:
            fwd = ctx.child()
            payload = dict(payload)
            payload["traceparent"] = fwd.to_traceparent()
            span_args.update(trace_id=ctx.trace_id, span_id=fwd.span_id,
                             parent_id=ctx.span_id)
        with replica.lock:
            replica.inflight += 1
        self._req_counter.inc(replica=replica.name)
        t0 = time.perf_counter()
        try:
            with self.tracer.span("forward", **span_args):
                faults.stall("router_replica_hang")
                req = urllib.request.Request(
                    replica.url + "/generate",
                    data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    body = json.load(r)
                    if not isinstance(body, dict):
                        raise ValueError(f"non-object reply: {body!r}")
                    replica.note_request_success()
                    with self._lat_lock:
                        self._latencies.append(time.perf_counter() - t0)
                    return r.status, body, None
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read() or b"{}")
            except (ValueError, OSError):
                body = {}
            replica.note_request_success()  # transport worked
            self._err_counter.inc(
                replica=replica.name,
                code=str(body.get("code", e.code)),
            )
            retry_after = None
            ra = e.headers.get("Retry-After")
            if ra is not None:
                try:
                    retry_after = float(ra)
                except ValueError:
                    pass
            return e.code, body, retry_after
        except (urllib.error.URLError, TimeoutError, ConnectionError,
                OSError, ValueError) as e:
            timed_out = isinstance(e, TimeoutError) or isinstance(
                getattr(e, "reason", None), TimeoutError
            )
            if timed_out and timeout_is_deadline:
                self._err_counter.inc(
                    replica=replica.name, code="deadline"
                )
                return 504, {
                    "error": f"request deadline expired after "
                             f"{timeout:.3f}s waiting on replica "
                             f"{replica.name}",
                    "code": "deadline",
                }, None
            # ValueError = truncated/garbage reply body — a replica
            # SIGKILLed mid-response looks like this, and it must fail
            # over like any other transport death, not surface a 500
            if replica.note_failure(time.monotonic()):
                self._eject_counter.inc(replica=replica.name)
                self.events.emit("replica_ejected",
                                 replica=replica.name, via="request",
                                 error=repr(e))
                print(f"[router] replica {replica.name} ejected "
                      f"(request transport failure: {e!r})",
                      file=sys.stderr)
            self._err_counter.inc(
                replica=replica.name, code="unreachable"
            )
            return -1, {
                "error": f"replica {replica.name} unreachable: {e!r}",
                "code": "replica_unreachable",
            }, None
        finally:
            with replica.lock:
                replica.inflight -= 1

    def _hedge_budget(self) -> Optional[float]:
        """Seconds to wait before hedging, derived from observed p99
        latency; None = hedging off."""
        if self.cfg.hedge_factor <= 0:
            return None
        with self._lat_lock:
            xs = sorted(self._latencies)
        if not xs:
            return max(self.cfg.hedge_min_s, 0.0)
        p99 = xs[min(len(xs) - 1, int(0.99 * len(xs)))]
        return max(self.cfg.hedge_min_s, self.cfg.hedge_factor * p99)

    def _attempt(self, replica: Replica, payload: dict, timeout: float,
                 exclude: Sequence[str],
                 timeout_is_deadline: bool = False, ctx=None):
        """One failover attempt, with an optional hedged twin. Returns
        ``(status, body, retry_after, replica, hedged)`` where
        ``replica`` is the one whose reply was used."""
        budget = self._hedge_budget()
        if budget is None:
            status, body, ra = self._forward(
                replica, payload, timeout, timeout_is_deadline, ctx=ctx
            )
            return status, body, ra, replica, False

        cond = threading.Condition()
        results: List[Tuple[int, dict, Optional[float], Replica]] = []
        expected = [1]

        def run(rep: Replica) -> None:
            out = self._forward(rep, payload, timeout,
                                timeout_is_deadline, ctx=ctx)
            with cond:
                results.append((*out, rep))
                cond.notify_all()

        threading.Thread(target=run, args=(replica,), daemon=True).start()
        hedged = False
        end = time.monotonic() + timeout + 1.0
        with cond:
            if not results:
                cond.wait(budget)
            if not results:
                # primary is slow: fire the hedge on a different replica
                other = self.pick(
                    exclude=tuple(exclude) + (replica.url,)
                )
                if other is not None:
                    hedged = True
                    self._hedge_counter.inc()
                    self.tracer.instant(
                        "hedge", primary=replica.name,
                        hedge=other.name,
                        **(instant_args(ctx) if ctx is not None else {}),
                    )
                    self.events.emit(
                        "request_hedged", primary=replica.name,
                        hedge=other.name,
                        trace_id=(
                            ctx.trace_id if ctx is not None else None
                        ),
                    )
                    threading.Thread(
                        target=run, args=(other,), daemon=True
                    ).start()
                    expected[0] = 2
            while True:
                if any(s == 200 for s, _, _, _ in results):
                    break
                if len(results) >= expected[0]:
                    break
                left = end - time.monotonic()
                if left <= 0 or not cond.wait(min(left, 1.0)):
                    if time.monotonic() >= end:
                        break
            done = list(results)
        for status, body, ra, rep in done:
            if status == 200:
                if hedged and rep is not replica:
                    self._hedge_win_counter.inc()
                return status, body, ra, rep, hedged
        if done:
            # no winner: report the primary's failure when it answered,
            # else whatever the hedge saw
            for status, body, ra, rep in done:
                if rep is replica:
                    return status, body, ra, rep, hedged
            status, body, ra, rep = done[0]
            return status, body, ra, rep, hedged
        return -1, {
            "error": f"replica {replica.name} did not answer in time",
            "code": "replica_unreachable",
        }, None, replica, hedged

    def _pick_for_attempt(self, session_id: Optional[str],
                          tried: Sequence[str],
                          end: Optional[float]) -> Optional[Replica]:
        """Pick with graceful degradation: prefer an un-tried eligible
        replica; fall back to RE-trying one that recovered (a rebooted
        replica beats a guaranteed failure); and when nothing at all is
        eligible, wait up to ``wait_for_replica_s`` (bounded by the
        request deadline) — that bridges the sub-second windows of a
        rolling restart where one replica is draining and the other is
        mid-re-admission."""
        wait_end = time.monotonic() + self.cfg.wait_for_replica_s
        if end is not None:
            wait_end = min(wait_end, end)
        while True:
            replica = self.pick(session_id=session_id, exclude=tried)
            if replica is None and tried:
                replica = self.pick(session_id=session_id)
            if replica is not None:
                return replica
            if time.monotonic() >= wait_end:
                return None
            self._sleep(min(
                0.05, max(0.001, wait_end - time.monotonic())
            ))

    # -- the request path ----------------------------------------------

    def _shed_retry_after(self, priority: str = "normal") -> float:
        """Retry-After seconds for a shed reply: the admission
        controller's honest fleet-capacity prediction when predictive
        admission is on, else the static configured default."""
        if self.admission is not None:
            return self.admission.retry_after_s(priority)
        return self.cfg.shed_retry_after_s

    def handle_generate(self, payload: dict) -> Tuple[int, dict, dict]:
        """Route one /generate request; returns ``(status, body,
        headers)``. Implements admission shedding, failover across
        distinct replicas under the deadline budget, Retry-After
        capping, affinity, and response attribution. Every request
        gets a trace context — client-supplied ``traceparent`` or
        minted here — propagated to the replica on each attempt and
        echoed as ``trace_id`` in every reply, success or failure."""
        ctx = trace_from_payload(payload)
        session_id = payload.get("session_id")
        if session_id is not None:
            session_id = str(session_id)
        budget = self.cfg.default_deadline_s
        try:
            client_deadline = float(payload.get("deadline_s") or 0.0)
        except (TypeError, ValueError):
            client_deadline = 0.0
        if client_deadline > 0:
            budget = (
                min(budget, client_deadline) if budget > 0
                else client_deadline
            )
        end = time.monotonic() + budget if budget > 0 else None
        priority = str(payload.get("priority") or "normal")
        if self.admission is not None:
            # proactive predictive shed: when the fleet's measured
            # service rate says this class's backlog will not clear
            # within its bound, refuse NOW with the honest wait instead
            # of burning failover attempts and the client's deadline
            decision = self.admission.admit(priority)
            if not decision.admitted:
                self._shed_counter.inc()
                self._admission_shed_counter.inc(priority=priority)
                self.events.emit(
                    "request_shed", trace_id=ctx.trace_id,
                    reason=decision.reason, priority=priority,
                    predicted_wait_s=decision.predicted_wait_s,
                )
                return 503, {
                    "error": "admission shed: " + decision.reason,
                    "code": "admission_shed",
                    "trace_id": ctx.trace_id,
                }, {"Retry-After": _fmt_secs(decision.retry_after_s)}
        shed_headers = {
            "Retry-After": _fmt_secs(self._shed_retry_after(priority))
        }
        # resume-by-replay bookkeeping: every routed request carries a
        # journal id the replica echoes in GET /inflight, so the probe
        # loop can harvest its emitted tokens. Replay needs token-level
        # prompts (text prompts stay on the plain-retry rung). Each
        # replay ATTEMPT gets a FRESH id: a replayed submission's
        # /inflight tokens are continuation-only, and harvesting them
        # under the old id would mis-position them in the journal.
        raw_prompt = payload.get("prompt_ids")
        orig_prompt = (
            [int(t) for t in raw_prompt] if raw_prompt is not None else None
        )
        jid = uuid.uuid4().hex
        payload = dict(payload)
        payload["journal_id"] = jid
        self.journal.begin(jid)
        try:
            remaining_max = int(payload.get("max_new_tokens", 16))
        except (TypeError, ValueError):
            remaining_max = 16
        cur_prompt = orig_prompt
        replay_prefix: List[int] = []
        tried: List[str] = []
        last: Optional[Tuple[int, dict, dict]] = None
        attempt = 0

        def _done(status: int, body: dict, headers: dict):
            self.journal.finish(jid)
            self._journal_bytes_gauge.set(self.journal.stats()["bytes"])
            body.setdefault("trace_id", ctx.trace_id)
            self.events.emit(
                "request_finished" if status == 200 else "request_failed",
                status=status, trace_id=ctx.trace_id, attempts=attempt,
                replica=body.get("replica"), code=body.get("code"),
            )
            return status, body, headers

        try:
            while True:
                with self.tracer.span("pick", attempt=attempt,
                                      **child_span_args(ctx)):
                    replica = self._pick_for_attempt(session_id, tried, end)
                if replica is None:
                    if last is not None:
                        return _done(*last)
                    # nothing eligible within the wait budget: shed typed
                    self._shed_counter.inc()
                    self.events.emit("request_shed", trace_id=ctx.trace_id)
                    return _done(503, {
                        "error": "no replica available "
                                 "(all ejected, draining, or not ready)",
                        "code": "no_replica",
                    }, shed_headers)
                timeout = 600.0
                timeout_is_deadline = False
                if end is not None:
                    timeout = max(0.05, end - time.monotonic())
                    timeout_is_deadline = True
                status, body, retry_after, used, hedged = self._attempt(
                    replica, payload, timeout, tried, timeout_is_deadline,
                    ctx=ctx,
                )
                attempt += 1
                if status == 200 and body.get("code") == "migrated":
                    # the source drained and live-migrated this request
                    # mid-decode: follow the continuation to the
                    # destination and collect the COMPLETE reply there.
                    # The destination can itself drain while decoding the
                    # imported continuation (one-at-a-time rolling restarts
                    # with migrate-out pre-drain do this naturally), in
                    # which case /migrate/await answers ANOTHER forwarding
                    # pointer — follow the chain hop-bounded; ONLY a 200
                    # without code=="migrated" is a real reply, anything
                    # else drops to the replay rung below.
                    dest = str(body.get("dest") or "").rstrip("/")
                    mid = str(body.get("migrate_id") or "")
                    no_pointer = {
                        "error": "migrated reply carried no destination",
                        "code": "migrate_bad_pointer",
                    }
                    astatus, abody = -1, dict(no_pointer)
                    hops = 0
                    while dest and mid:
                        hops += 1
                        if hops > self.cfg.migrate_max_hops:
                            astatus, abody = -1, {
                                "error": "migration chain exceeded "
                                         f"{self.cfg.migrate_max_hops} hops",
                                "code": "migrate_hop_limit",
                            }
                            break
                        if session_id is not None:
                            # affinity must follow the moved state
                            # immediately — every hop, not just the first
                            self.repin(session_id, dest)
                        await_t = 600.0
                        if end is not None:
                            await_t = max(0.05, end - time.monotonic())
                        astatus, abody = self._await_migrated(
                            dest, mid, await_t, ctx=ctx
                        )
                        if not (astatus == 200
                                and abody.get("code") == "migrated"):
                            break
                        self.events.emit(
                            "migrate_chained", trace_id=ctx.trace_id,
                            hop=hops, source=dest,
                            dest=str(abody.get("dest") or ""),
                        )
                        dest = str(abody.get("dest") or "").rstrip("/")
                        mid = str(abody.get("migrate_id") or "")
                        astatus, abody = -1, dict(no_pointer)
                    if astatus == 200:
                        self._migration_counter.inc(outcome="migrated")
                        if replay_prefix:
                            abody["tokens"] = (
                                replay_prefix + list(abody.get("tokens") or [])
                            )
                            abody["prompt_ids"] = orig_prompt
                        drep = next(
                            (r for r in self.replicas if r.url == dest), None
                        )
                        abody["replica"] = (
                            drep.name if drep is not None else dest
                        )
                        abody["attempts"] = attempt
                        abody["hedged"] = hedged
                        abody["migrated"] = True
                        return _done(200, abody, {})
                    # destination lost the continuation (crash between
                    # import and finish): typed, counted, and dropped into
                    # the normal retriable ladder — the replay rung below
                    # reconstructs from the journal
                    self._migration_counter.inc(outcome="migrate_failed")
                    status, body, retry_after = 503, {
                        "error": f"migrated continuation lost at {dest}: "
                                 + str(abody.get("error")
                                       or abody.get("code") or astatus),
                        "code": "migrate_await_failed",
                    }, None
                elif status == 200:
                    if replay_prefix:
                        # this attempt decoded only the tail; splice the
                        # journaled prefix back and restore the original
                        # prompt so the client sees one seamless reply
                        body["tokens"] = (
                            replay_prefix + list(body.get("tokens") or [])
                        )
                        body["prompt_ids"] = orig_prompt
                        body["replayed"] = True
                        self._migration_counter.inc(outcome="replayed")
                    body["replica"] = used.name
                    body["attempts"] = attempt
                    body["hedged"] = hedged
                    return _done(200, body, {})
                retriable = status == -1 or (
                    status == 503
                    and body.get("code") not in NON_RETRIABLE_503_CODES
                )
                if not retriable:
                    # non-recoverable (504 deadline, timeout,
                    # engine_failed, 4xx/5xx): pass through, attributed
                    body.setdefault("replica", used.name)
                    return _done(status, body, {})
                tried.append(replica.url)
                if used is not replica and used.url not in tried:
                    tried.append(used.url)  # a failed hedge also counts
                if orig_prompt is not None:
                    toks = self.journal.tokens(jid)
                    if toks:
                        # resume-by-replay: the dead attempt already
                        # emitted these tokens; resubmit prompt+prefix as
                        # a prefill with key_offset carrying the key-chain
                        # position, so the peer's continuation is
                        # bit-identical — no page transfer, no lost work
                        replay_prefix = replay_prefix + toks
                        remaining_max = max(0, remaining_max - len(toks))
                        reason = self._replay_finish_reason(
                            replay_prefix, payload, remaining_max
                        )
                        if reason is not None:
                            # the source died AFTER finishing the
                            # generation but before replying: the journal
                            # holds the complete answer — synthesize it
                            self._migration_counter.inc(outcome="replayed")
                            return _done(200, {
                                "request_id": -1,
                                "prompt_ids": orig_prompt,
                                "tokens": replay_prefix,
                                "finish_reason": reason,
                                "ttft_ms": 0.0,
                                "replayed": True,
                                "attempts": attempt,
                                "hedged": hedged,
                            }, {})
                        cur_prompt = list(cur_prompt) + toks
                        self.journal.finish(jid)
                        jid = uuid.uuid4().hex
                        self.journal.begin(jid)
                        payload = dict(payload)
                        payload["prompt_ids"] = cur_prompt
                        payload["key_offset"] = len(replay_prefix)
                        payload["max_new_tokens"] = max(1, remaining_max)
                        payload["journal_id"] = jid
                        self.events.emit(
                            "request_replayed", trace_id=ctx.trace_id,
                            journaled=len(toks),
                            total_prefix=len(replay_prefix),
                        )
                capped_ra = None
                if retry_after is not None:
                    capped_ra = min(retry_after, self.cfg.retry_after_cap_s)
                headers = {
                    "Retry-After": _fmt_secs(
                        capped_ra if capped_ra is not None
                        else self._shed_retry_after(priority)
                    )
                }
                last = (503 if status == -1 else status, body, headers)
                if attempt >= self.cfg.max_attempts:
                    return _done(*last)
                delay = backoff_delay(
                    attempt - 1, base=self.cfg.retry_base_s,
                    cap=self.cfg.retry_cap_s, retry_after=capped_ra,
                    rng=self._rng,
                )
                if end is not None and time.monotonic() + delay >= end:
                    # deadline would expire mid-backoff: surface the last
                    # typed failure instead of manufacturing a 504
                    return _done(*last)
                self._retry_counter.inc()
                self.tracer.instant(
                    "retry", attempt=attempt, failed=used.name,
                    code=str(body.get("code", status)), **instant_args(ctx),
                )
                self.events.emit(
                    "request_retried", trace_id=ctx.trace_id,
                    attempt=attempt, failed=used.name,
                    code=body.get("code"),
                )
                self._sleep(delay)
        finally:
            # EVERY exit path retires the live journal entry —
            # including an unexpected exception that bypasses
            # _done (do_POST's catch-all 500 path). finish() is
            # idempotent, so _done's accounting stays the happy
            # path and this is a no-op there; without it a
            # crashed attempt leaks its entry into _live forever
            # (ReplayJournal only evicts finished entries).
            self.journal.finish(jid)
            self._journal_bytes_gauge.set(
                self.journal.stats()["bytes"]
            )

    # -- fleet observability -------------------------------------------

    def eligible_count(self) -> int:
        n = sum(1 for r in self.replicas if r.eligible())
        self._eligible_gauge.set(n)
        return n

    def health(self) -> dict:
        return {
            "ok": self.eligible_count() > 0,
            "eligible": self.eligible_count(),
            "replicas": [r.snapshot() for r in self.replicas],
        }

    def fleet_metrics(self, now: Optional[float] = None) -> str:
        """One exposition for the whole fleet (``GET /fleet/metrics``):
        the replicas' last-probed ``/metrics`` bodies summed/labeled
        (see :func:`aggregate_fleet_metrics`) plus the router's own
        registry, plus a synthesized ``fleet_replica_up`` gauge from
        the health state machine — so one scrape answers both "how
        much work is the fleet doing" and "who is in rotation".

        Staleness is bounded and ADVERTISED: every probe-stamped body
        carries a ``fleet_scrape_age_seconds{replica=...}`` gauge, and
        bodies older than ``cfg.metrics_max_age_s`` (a blackholed or
        wedged replica whose last scrape is ancient) are EXCLUDED from
        the aggregate rather than silently served as current — a
        consumer judging SLO burn must see the replica as missing, not
        as healthy-at-its-last-good-moment. Bodies with no stamp
        (installed out-of-band, age unknowable) stay included for
        back-compat."""
        bodies: Dict[str, str] = {}
        up_lines = ["# TYPE fleet_replica_up gauge"]
        age_lines = ["# TYPE fleet_scrape_age_seconds gauge"]
        max_age = self.cfg.metrics_max_age_s
        now = time.monotonic() if now is None else now
        for r in self.replicas:
            with r.lock:
                text = r.metrics_text
                state = r.state
                stamped_t = r.metrics_t
            age = None if stamped_t is None else max(0.0, now - stamped_t)
            if age is not None:
                age_lines.append(
                    f'fleet_scrape_age_seconds{{replica="{r.name}"}}'
                    f" {age:.3f}"
                )
            if text and (age is None or max_age <= 0 or age <= max_age):
                bodies[r.name] = text
            up_lines.append(
                f'fleet_replica_up{{replica="{r.name}",'
                f'state="{state}"}} {1 if state == UP else 0}'
            )
        own = (
            self.registry.render()
            + "\n".join(up_lines) + "\n"
            + ("\n".join(age_lines) + "\n" if len(age_lines) > 1 else "")
        )
        return aggregate_fleet_metrics(bodies, own=own)


def _fmt_secs(secs: float) -> str:
    """Retry-After header value: integer seconds, floored at 1 (the
    header is delta-seconds; 0 invites an instant re-pile-on)."""
    return str(max(1, int(secs)))


def _make_handler(router: Router):
    class Handler(BaseHTTPRequestHandler):
        def _reply(self, code: int, payload: dict,
                   headers: Optional[dict] = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path in ("/metrics", "/fleet/metrics"):
                # /metrics = the router's own registry; /fleet/metrics
                # = one scrape target for the whole fleet (per-replica
                # bodies summed/labeled from the probe loop's parses)
                text = (
                    router.registry.render() if self.path == "/metrics"
                    else router.fleet_metrics()
                )
                body = text.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", METRICS_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/health":
                self._reply(200, router.health())
            elif self.path == "/ready":
                n = router.eligible_count()
                if n > 0:
                    self._reply(200, {"ready": True, "eligible": n})
                else:
                    self._reply(
                        503, {"ready": False, "eligible": 0},
                        headers={"Retry-After": _fmt_secs(
                            router.cfg.shed_retry_after_s
                        )},
                    )
            else:
                self._reply(404, {"error": f"unknown path {self.path}",
                                  "code": "bad_request"})

        def do_POST(self):
            if self.path not in ("/generate", "/drain"):
                self._reply(404, {"error": f"unknown path {self.path}",
                                  "code": "bad_request"})
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
                payload = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(payload, dict):
                    raise ValueError("request body must be a JSON object")
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                self._reply(400, {"error": str(e), "code": "bad_request"})
                return
            if self.path == "/drain":
                # migrate a replica's in-flight requests to peers —
                # tools/fleet.py calls this before a rolling restart
                url = str(payload.get("replica") or "").rstrip("/")
                if not url:
                    self._reply(400, {"error": "missing 'replica' url",
                                      "code": "bad_request"})
                    return
                try:
                    self._reply(200, router.migrate_out(url))
                except Exception as e:
                    self._reply(500, {"error": f"drain error: {e!r}",
                                      "code": "internal"})
                return
            try:
                status, body, headers = router.handle_generate(payload)
            except Exception as e:  # router bug: typed 500, keep serving
                self._reply(500, {"error": f"router error: {e!r}",
                                  "code": "internal"})
                return
            self._reply(status, body, headers)

        def log_message(self, *a):  # quiet by default
            pass

    return Handler


def serve_router(router: Router, host: str = "127.0.0.1",
                 port: int = 8000) -> ThreadingHTTPServer:
    """Build the router's HTTP server (not yet serving; call
    serve_forever())."""
    return ThreadingHTTPServer((host, port), _make_handler(router))


def main() -> None:
    """CLI: route traffic over already-running replicas (tools/fleet.py
    launches replicas AND a router in one command)."""
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--target", action="append", required=True,
                   help="replica base URL (repeat per replica), e.g. "
                        "http://127.0.0.1:8101")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--probe-interval", type=float, default=0.5)
    p.add_argument("--eject-after", type=int, default=3)
    p.add_argument("--readmit-after", type=int, default=2)
    p.add_argument("--max-attempts", type=int, default=3)
    p.add_argument("--deadline", type=float, default=120.0,
                   help="total per-request budget in seconds (0 = none)")
    p.add_argument("--hedge-factor", type=float, default=0.0,
                   help="hedge a request stuck past this multiple of "
                        "observed p99 latency (0 = hedging off)")
    p.add_argument("--hedge-min", type=float, default=0.25)
    p.add_argument("--trace-path", default=None,
                   help="write a Chrome-trace-event JSON of "
                        "pick/forward/retry/hedge spans (stitch with "
                        "replica traces via tools/trace_stitch.py)")
    p.add_argument("--event-log", default=None,
                   help="append structured JSONL events (request "
                        "finished/failed/retried, replica ejection/"
                        "re-admission; obs/events.py)")
    args = p.parse_args()

    cfg = RouterConfig(
        probe_interval_s=args.probe_interval,
        eject_after=args.eject_after,
        readmit_after=args.readmit_after,
        max_attempts=args.max_attempts,
        default_deadline_s=args.deadline,
        hedge_factor=args.hedge_factor,
        hedge_min_s=args.hedge_min,
    )
    tracer = None
    if args.trace_path:
        from differential_transformer_replication_tpu.obs.spans import (
            SpanTracer,
        )

        tracer = SpanTracer(args.trace_path, process_name="router")
    events = None
    if args.event_log:
        from differential_transformer_replication_tpu.obs.events import (
            EventLog,
        )

        events = EventLog(args.event_log, process="router")
    router = Router(args.target, cfg, tracer=tracer,
                    events=events).start()
    httpd = serve_router(router, args.host, args.port)
    print(f"[router] fronting {len(router.replicas)} replicas — "
          f"POST http://{args.host}:{args.port}/generate, fleet state "
          f"at GET http://{args.host}:{args.port}/health, one-scrape "
          f"fleet metrics at GET /fleet/metrics")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        router.close()
        if tracer is not None:
            tracer.close()
        if events is not None:
            events.close()


if __name__ == "__main__":
    main()
