"""Paged KV-cache pool with radix-tree shared-prefix reuse.

The slot-pool engine (serving/engine.py) historically allocated one
contiguous ``block_size``-long KV ring per slot, so concurrent capacity
was bounded by WORST-CASE context — a 6-token request held the same HBM
as a 512-token one — and every request re-prefilled its prompt from
scratch. This module is the allocator side of the paged replacement
(vLLM's PagedAttention and SGLang's RadixAttention are the shape):

- **Fixed-size pages.** Device KV state lives in one pool of
  ``total_pages`` pages of ``page_size`` tokens each
  (models/decode.py:``init_cache_paged``). A slot's logical ring of
  ``block_size`` tokens maps onto physical pages through a per-slot
  PAGE TABLE row — ``(num_slots, pages_per_slot)`` int32, physical page
  per logical page. Page 0 is a reserved TRASH page: unallocated
  logical pages and inactive rows' decode writes are redirected there,
  which is how the jitted decode step stays mask-free and recompile-free
  while pages churn (the device never sees an invalid index).
- **Host-only bookkeeping.** This module never imports jax: admission
  planning, refcounts, the radix tree and eviction are pure host state
  guarded by ONE lock (``self._lock`` — /health and bench threads read
  :meth:`stats` while the engine thread mutates; graftlint GL301/GL6xx
  machine-check the discipline). Device-side copies a plan requires
  (COW forks) are returned as ``(src_page, dst_page)`` pairs for the
  engine to apply; the engine MUST apply them before its next pool
  call (single engine thread — an evicted fork source must not be
  reused before its copy executes).
- **Radix-tree prefix cache.** Retired prompts donate their KV pages
  to a refcounted radix tree keyed on prompt token ids: one node per
  page, children keyed by the token tuple the child page covers. A new
  request walks the tree, SHARES fully-matching pages (refcount++,
  prefill skips them — the near-zero-TTFT path for common system
  prompts) and copy-on-write FORKS at a partial-page boundary: the
  longest common prefix of a cached page is copied into a fresh
  private page and prefill resumes mid-page. Matches are capped at
  ``len(prompt) - 1`` so at least one prompt token is always
  recomputed — its logits seed the first sampled token.
- **Admission keys on free pages, not slots.** :meth:`plan_admission`
  reserves the request's worst-case private pages up front
  (``ceil(min(prompt + max_new, block_size) / page_size)`` minus the
  shared full pages), so a mid-decode allocation can never fail and
  short requests hold proportionally little HBM — sizing the pool
  below ``num_slots * pages_per_slot`` (``ServingConfig.kv_pool_pages``)
  is exactly how paging converts short-context traffic into MORE
  concurrent slots at equal HBM. Unreferenced cached prefixes are
  LRU-evicted to satisfy a reservation; when even eviction cannot, the
  request WAITS (FCFS head-of-line), and a request that could never
  fit — or a ``page_exhaust`` fault (utils/faults.py) — raises the
  typed, retriable :class:`PagePoolExhaustedError` that surfaces as
  the serving 503 shed path (serving/server.py).

Byte accounting is int8-aware (:func:`page_bytes`): an int8 KV page
carries 1-byte values plus the fp32 per-vector scale planes
(ops/decode_attention.py:quantize_kv), about 0.53x the bf16 bytes.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


class PagePoolExhaustedError(RuntimeError):
    """The page pool cannot satisfy an allocation. Typed and RETRIABLE
    by default (the pool drains as requests retire and cached prefixes
    evict — a client that backs off lands on a drained pool); a request
    whose worst case exceeds the whole pool can never fit and carries
    ``retriable = False``. HTTP maps this to the 503 shed path with a
    machine-readable ``page_pool_exhausted`` code."""

    retriable = True


@dataclass
class Admission:
    """One planned admission: how much prefill the prefix cache covers,
    the device copies the engine must apply (COW forks), and the
    host-tier pages to promote. ``cached_len`` counts DEVICE-resident
    tokens plus every planned promotion; ``device_cached`` counts only
    the device-resident part — when a promotion fails mid-apply the
    engine truncates its effective cached length back toward
    ``device_cached`` (recompute fallback, never garbage KV)."""

    cached_len: int  # prompt tokens covered, promotions included
    copies: List[Tuple[int, int]] = field(default_factory=list)
    hit: bool = False
    # host-tier promotions: (dst physical page, TierEntry) per promoted
    # full page, in prompt order starting at device_cached. The payload
    # was fetched (and checksum-verified) at plan time; the engine
    # re-verifies at injection and degrades to recompute on mismatch.
    promotes: List[Tuple[int, object]] = field(default_factory=list)
    device_cached: int = 0  # tokens already resident in HBM


class _Node:
    """One cached page: ``key`` is the token tuple it covers (length ==
    ``filled``; < page_size for a partial tail page), ``page`` the
    physical page id, ``refs`` the number of slots currently sharing
    it. Children are keyed by their OWN token tuples."""

    __slots__ = ("key", "page", "filled", "children", "refs",
                 "last_use", "parent")

    def __init__(self, key: tuple, page: int, parent: "_Node",
                 clock: int):
        self.key = key
        self.page = page
        self.filled = len(key)
        self.children: Dict[tuple, "_Node"] = {}
        self.refs = 0
        self.last_use = clock
        self.parent = parent


def _common_prefix(a: Sequence[int], b: Sequence[int]) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


_DTYPE_BYTES = {"bfloat16": 2, "float16": 2, "float32": 4}


def page_bytes(cfg, page_size: int) -> int:
    """HBM bytes one physical page holds across ALL layers, int8-aware
    (int8 K/V values plus the fp32 per-vector scale planes). Pure
    arithmetic over the ModelConfig — no jax import, so sizing math is
    available to host-only tools."""
    S = {"control": 1, "diff": 2, "ndiff": cfg.n_terms}[cfg.model]
    H, d, dv = cfg.n_head, cfg.head_size, cfg.value_size
    store = cfg.kv_cache_dtype
    if store == "int8":
        per_layer = (
            S * H * page_size * d          # k int8
            + H * page_size * dv           # v int8
            + S * H * page_size * 4        # k_scale fp32
            + H * page_size * 4            # v_scale fp32
        )
    else:
        b = _DTYPE_BYTES["bfloat16" if store == "bf16"
                         else cfg.compute_dtype]
        per_layer = (S * H * page_size * d + H * page_size * dv) * b
    return per_layer * cfg.n_layer


class PagePool:
    """Host-side page allocator + radix prefix cache (module docstring).

    All mutable state is guarded by ``self._lock``: the engine thread
    plans/releases while /health handlers and the bench read
    :meth:`stats` concurrently. Nothing blocking ever runs under the
    lock (graftlint GL602)."""

    TRASH = 0  # reserved physical page: unallocated / inactive writes
    # observation window for the page drain-rate estimate behind
    # PagePoolExhaustedError's Retry-After (estimated_drain_s)
    DRAIN_WINDOW_S = 30.0

    def __init__(self, *, page_size: int, pages_per_slot: int,
                 num_slots: int, total_pages: int,
                 prefix_cache: bool = True, tier=None):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if total_pages < pages_per_slot + 2:
            raise ValueError(
                f"total_pages ({total_pages}) must hold at least one "
                f"max-length request plus the trash page "
                f"({pages_per_slot + 1} + 1)"
            )
        self.page_size = page_size
        self.pages_per_slot = pages_per_slot
        self.num_slots = num_slots
        self.total_pages = total_pages
        self.capacity = total_pages - 1  # page 0 is the trash page
        self.prefix_cache = prefix_cache
        # optional host-RAM page tier (serving/host_tier.py): evicted
        # full radix pages demote there instead of vanishing, and
        # admission planning consults it past the device match. Lock
        # order is PagePool._lock -> HostTier._lock (GL601): the tier
        # never calls back into the pool.
        self._tier = tier
        self._lock = threading.Lock()
        self._clock = 0
        self._force_exhausted = False
        # import here to keep module import light; np is host-side only
        import numpy as np

        self._np = np
        with self._lock:
            self._reset_locked()

    # -- lifecycle ----------------------------------------------------

    def _reset_locked(self) -> None:
        np = self._np
        self._free: List[int] = list(range(1, self.total_pages))
        self._tables = np.zeros(  # graftlint: threadsafe (_locked helper: every caller holds self._lock)
            (self.num_slots, self.pages_per_slot), np.int32
        )
        self._slot_private: List[List[int]] = [
            [] for _ in range(self.num_slots)
        ]
        self._slot_nodes: List[List[_Node]] = [
            [] for _ in range(self.num_slots)
        ]
        self._root = _Node((), self.TRASH, None, 0)  # graftlint: threadsafe (_locked helper: every caller holds self._lock)
        self._nodes: List[_Node] = []
        # demotion plans awaiting the engine: (full token prefix, page)
        # per evicted full page. The engine drains this IMMEDIATELY
        # after every planning call — before applying copies/promotes
        # and before any prefill — so the page's device bytes are still
        # the evicted prefix when captured. A pool reset discards them
        # (the device data is untrusted after a crash).
        self._pending_demotions: List[Tuple[tuple, int]] = []  # graftlint: threadsafe (_locked helper: every caller holds self._lock)
        # recent page-free events (monotonic timestamp, count) — the
        # observed drain throughput behind estimated_drain_s(), which
        # turns PagePoolExhaustedError's Retry-After into a measure of
        # actual pool drain time instead of a static queue bound
        if not hasattr(self, "_freed_log"):
            self._freed_log: List[Tuple[float, int]] = []  # graftlint: threadsafe (_locked helper: every caller holds self._lock)
        # monotonic counters (prometheus semantics) survive reset —
        # a crash-rebuild must not zero the fleet's hit-rate series
        for name in ("hits", "misses", "evictions", "cow_forks",
                     "tier_hits"):
            if not hasattr(self, "_" + name):
                setattr(self, "_" + name, 0)

    def reset(self) -> None:
        """Drop every table, reservation and cached prefix; every page
        returns to the free list. The crash-recovery path
        (``ServingEngine.reset_after_crash``): a poisoned cached prefix
        (``prefix_corrupt`` fault) trips the finite-logits guard, and
        the supervised restart lands here — the poisoned pages are
        evicted wholesale instead of ever serving garbage tokens."""
        with self._lock:
            self._reset_locked()

    def force_exhaust(self) -> None:
        """Fault hook (``page_exhaust@N``): the next admission plan
        raises :class:`PagePoolExhaustedError` regardless of free
        pages, proving the typed-shed path end to end."""
        with self._lock:
            self._force_exhausted = True

    # -- sizing -------------------------------------------------------

    def pages_needed(self, prompt_len: int, max_new: int) -> int:
        """Worst-case PRIVATE pages a request may hold (no sharing)."""
        M = self.pages_per_slot * self.page_size
        total = min(prompt_len + max_new, M)
        return -(-total // self.page_size)

    # -- admission ----------------------------------------------------

    def plan_admission(self, slot: int, prompt: Sequence[int],
                       max_new: int) -> Optional[Admission]:
        """Reserve everything the request will ever write, consulting
        the radix cache first. Returns None when the pool is too full
        right now (the scheduler keeps the request queued, FCFS);
        raises :class:`PagePoolExhaustedError` when the request can
        NEVER fit or the ``page_exhaust`` fault is armed. On success
        the slot's page-table row is live and ``Admission.copies``
        lists the fork copies the engine must apply before its next
        pool call."""
        with self._lock:
            if self._force_exhausted:
                self._force_exhausted = False
                raise PagePoolExhaustedError(
                    "page pool exhausted (fault-injected); retry later"
                )
            ps = self.page_size
            M = self.pages_per_slot * ps
            total = min(len(prompt) + max_new, M)
            total_pages = -(-total // ps)
            if total_pages > self.capacity:
                err = PagePoolExhaustedError(
                    f"request needs {total_pages} pages but the pool "
                    f"holds {self.capacity}; raise kv_pool_pages or "
                    "lower max_new_tokens"
                )
                err.retriable = False
                raise err
            rolls = len(prompt) + max_new > M
            full: List[_Node] = []
            fork: Optional[Tuple[_Node, int]] = None
            matched = 0
            if self.prefix_cache and not rolls:
                full, fork, matched = self._match_locked(prompt)
            device_cached = len(full) * ps
            # host-tier extension: where the device walk ended, keep
            # matching FULL pages against demoted prefixes. Payloads
            # are fetched (and checksum-verified) NOW, under the pool
            # lock (pool -> tier order, GL601), so a later tier
            # eviction cannot tear this plan. A tier hit supersedes a
            # partial COW fork at the same logical page — a full page
            # strictly dominates a partial one.
            tier_entries: List[object] = []
            if (self._tier is not None and self.prefix_cache
                    and not rolls):
                j = len(full)
                while (j + 1) * ps <= len(prompt) - 1:
                    ent = self._tier.get(tuple(prompt[:(j + 1) * ps]))
                    if ent is None:
                        break
                    tier_entries.append(ent)
                    j += 1
                if tier_entries:
                    fork = None
                    matched = j * ps
                    self._tier_hits += 1
            # pin the matched chain before eviction runs: a refs==0
            # cached node we are about to share must not be evicted to
            # satisfy our own reservation
            self._clock += 1
            for n in full:
                n.refs += 1
                n.last_use = self._clock
            if fork is not None:
                fork[0].refs += 1
                fork[0].last_use = self._clock
            need = total_pages - len(full)
            pages = self._take_pages_locked(need)
            if fork is not None:
                # the fork source is COPIED, not shared: unpin. The
                # engine applies the copy before any further pool call,
                # so the source cannot be evicted-and-reused first.
                fork[0].refs -= 1
            if pages is None:
                for n in full:
                    n.refs -= 1
                return None
            row = self._np.zeros(self.pages_per_slot, self._np.int32)
            for j, n in enumerate(full):
                row[j] = n.page
            for j, pg in zip(range(len(full), total_pages), pages):
                row[j] = pg
            self._tables[slot] = row
            self._slot_nodes[slot] = full
            self._slot_private[slot] = list(pages)
            copies: List[Tuple[int, int]] = []
            if fork is not None:
                copies.append((fork[0].page, pages[0]))
                self._cow_forks += 1
            # promoted pages land on the slot's FIRST private pages
            # (logical indices len(full)..): injected there they are
            # ordinary private prefix KV, donated back to the radix
            # tree at release like any prefilled page
            promotes = [
                (pages[t], ent) for t, ent in enumerate(tier_entries)
            ]
            if matched > 0:
                self._hits += 1
            else:
                self._misses += 1
            return Admission(cached_len=matched, copies=copies,
                             hit=matched > 0, promotes=promotes,
                             device_cached=device_cached)

    def _match_locked(self, prompt: Sequence[int]):
        """Longest cached prefix of ``prompt``, capped at
        ``len(prompt) - 1``: (fully-shared nodes, optional
        (node, tokens) partial fork, matched token count)."""
        ps = self.page_size
        limit = len(prompt) - 1
        node = self._root
        full: List[_Node] = []
        i = 0
        while limit - i > 0:
            rem = limit - i
            key = tuple(prompt[i:i + ps])
            child = node.children.get(key)
            if (child is not None and child.filled == ps
                    and rem >= ps):
                full.append(child)
                node = child
                i += ps
                continue
            # partial-page boundary: the best common prefix of any
            # child page is usable via a COW fork (K/V at position p
            # depends only on tokens <= p, so a prefix of a cached
            # page is valid K/V even when the tails diverge)
            best, best_t = None, 0
            for c in node.children.values():
                t = min(_common_prefix(c.key, prompt[i:i + c.filled]),
                        rem)
                if t > best_t:
                    best, best_t = c, t
            if best is not None:
                return full, (best, best_t), i + best_t
            break
        return full, None, i

    def _take_pages_locked(self, n: int) -> Optional[List[int]]:
        while len(self._free) < n:
            if not self._evict_one_locked():
                return None
        return [self._free.pop() for _ in range(n)]

    def _evict_one_locked(self) -> bool:
        """Free the least-recently-used unreferenced LEAF of the radix
        tree (interior nodes are pinned by their children: evicting a
        middle page would orphan the chain below it). The linear scan
        is deliberate: the node count is bounded by the page pool
        (hundreds, not thousands) and eviction only runs when an
        admission is already short on pages — simplicity beats an
        index here until profiles say otherwise."""
        victim = None
        for node in self._nodes:
            if node.refs == 0 and not node.children:
                if victim is None or node.last_use < victim.last_use:
                    victim = node
        if victim is None:
            return False
        if self._tier is not None and victim.filled == self.page_size:
            # demote instead of forget: plan a host capture of the
            # evicted FULL page (partial tails are rare — one per
            # prompt — and stay plain evictions). The HBM page is
            # freed either way; the engine captures its still-intact
            # bytes when it drains the plan, before any reuse writes.
            self._pending_demotions.append(  # graftlint: threadsafe (_locked helper: every caller holds self._lock)
                (self._node_prefix(victim), victim.page)
            )
        del victim.parent.children[victim.key]
        self._nodes.remove(victim)
        self._free.append(victim.page)
        self._note_freed_locked(1)
        self._evictions += 1  # graftlint: threadsafe (_locked helper: every caller holds self._lock)
        return True

    @staticmethod
    def _node_prefix(node: _Node) -> tuple:
        """The full token prefix a node's page covers (root -> node key
        concatenation) — the host tier's lookup key."""
        parts = []
        while node is not None and node.key:
            parts.append(node.key)
            node = node.parent
        out: List[int] = []
        for key in reversed(parts):
            out.extend(key)
        return tuple(out)

    def _note_freed_locked(self, n: int) -> None:
        """Record page-free events for the drain-rate estimate; the
        log is pruned to the observation window on every append."""
        now = time.monotonic()
        self._freed_log.append((now, n))  # graftlint: threadsafe (_locked helper: every caller holds self._lock)
        cutoff = now - self.DRAIN_WINDOW_S
        while self._freed_log and self._freed_log[0][0] < cutoff:
            self._freed_log.pop(0)

    def plan_resume(self, slot: int,
                    total_pages: int) -> Optional[List[int]]:
        """Reserve PRIVATE pages for a preempted request swapping back
        in: no radix matching — the request's full KV image (prompt
        AND generated tokens) is injected from its host-tier stash, so
        every page is privately owned from the start. Returns the
        allocated pages in logical order, or None when the pool cannot
        free enough right now (the request stays queued; the priority
        scheduler may preempt a lower class to make room)."""
        with self._lock:
            pages = self._take_pages_locked(total_pages)
            if pages is None:
                return None
            row = self._np.zeros(self.pages_per_slot, self._np.int32)
            for j, pg in enumerate(pages):
                row[j] = pg
            self._tables[slot] = row
            self._slot_nodes[slot] = []
            self._slot_private[slot] = list(pages)
            return pages

    def probe_prefix(self, prompt: Sequence[int]) -> int:
        """How many LEADING FULL PAGES of ``prompt`` this pool's radix
        tree holds on device — the migration dedup probe
        (serving/migrate.py): the source replica skips shipping pages
        the destination can copy device-locally. Read-only (no
        refcount, no eviction) and conservative: the chain is
        re-resolved under the lock at import time and a miss there
        degrades to a typed import failure, never garbage KV."""
        with self._lock:
            full, _fork, _matched = self._match_locked(prompt)
            return len(full)

    def chain_pages(self, prompt: Sequence[int],
                    n_pages: int) -> Optional[List[int]]:
        """Physical page ids of the first ``n_pages`` full-page radix
        nodes covering ``prompt``, or None when the chain is no longer
        fully cached (evicted between the dedup probe and the import —
        the race is closed by failing typed, not by pinning). Bumps
        each node's LRU clock; the caller (engine thread) must read the
        pages' device bytes before its next pool planning call, the
        same single-thread invariant COW forks rely on."""
        with self._lock:
            ps = self.page_size
            node = self._root
            out: List[int] = []
            for i in range(n_pages):
                key = tuple(prompt[i * ps:(i + 1) * ps])
                child = (
                    node.children.get(key) if len(key) == ps else None
                )
                if child is None or child.filled != ps:
                    return None
                self._clock += 1
                child.last_use = self._clock
                out.append(child.page)
                node = child
            return out

    def take_demotions(self) -> List[Tuple[tuple, int]]:
        """Drain the pending demotion plans (prefix key, freed page).
        The engine MUST call this immediately after EVERY planning call
        (plan_admission / plan_resume, success or not) and capture the
        named pages' device bytes before applying any copy, promote or
        prefill — freed pages are only ever handed back out by later
        planning calls on the same single engine thread, so the bytes
        are still the evicted prefix at capture time."""
        with self._lock:
            out, self._pending_demotions = self._pending_demotions, []
            return out

    def estimated_drain_s(self, pages_needed: int) -> Optional[float]:
        """Seconds until ``pages_needed`` pages drain at the observed
        free rate (evictions + releases over the last DRAIN_WINDOW_S)
        — the Retry-After a shed request should back off for. None
        when nothing freed recently (no basis for an estimate; callers
        fall back to their static default)."""
        with self._lock:
            if not self._freed_log:
                return None
            now = time.monotonic()
            cutoff = now - self.DRAIN_WINDOW_S
            freed = sum(n for t, n in self._freed_log if t >= cutoff)
            if freed <= 0:
                return None
            oldest = max(self._freed_log[0][0], cutoff)
            elapsed = max(now - oldest, 1e-3)
            rate = freed / elapsed
            return max(pages_needed, 1) / rate

    # -- release / cache insertion ------------------------------------

    def release(self, slot: int, prompt: Sequence[int],
                cacheable: bool) -> None:
        """Return a retiring slot's pages. Shared nodes are
        dereferenced; with ``cacheable`` (prompt fully prefilled, ring
        never rolled) the prompt's private pages are DONATED to the
        radix tree — full pages as shared nodes, the partial tail page
        as a forkable partial node — and only the decode-only pages go
        back to the free list."""
        with self._lock:
            self._clock += 1
            for n in self._slot_nodes[slot]:
                n.refs -= 1
                n.last_use = self._clock
            shared_full = len(self._slot_nodes[slot])
            private = list(self._slot_private[slot])
            row = self._tables[slot].copy()
            self._tables[slot] = self.TRASH
            self._slot_nodes[slot] = []
            self._slot_private[slot] = []
            donated: List[int] = []
            if cacheable and self.prefix_cache and len(prompt) > 0:
                donated = self._insert_locked(prompt, row, shared_full)
            freed = 0
            for pg in private:
                if pg not in donated:
                    self._free.append(pg)
                    freed += 1
            if freed:
                self._note_freed_locked(freed)

    def _insert_locked(self, prompt: Sequence[int], row,
                       shared_full: int) -> List[int]:
        """Donate the slot's prompt pages into the tree; returns the
        page ids the tree now owns. Pages duplicating an existing node
        are NOT donated (the caller frees them) — the tree stays
        canonical when identical prompts retire concurrently."""
        ps = self.page_size
        donated: List[int] = []
        node = self._root
        n_full = len(prompt) // ps
        for j in range(n_full):
            key = tuple(prompt[j * ps:(j + 1) * ps])
            child = node.children.get(key)
            if child is not None and child.filled == ps:
                node = child
                continue
            if j < shared_full:
                # the row held a shared page here but the node chain
                # diverged meanwhile (evicted + re-cached differently);
                # we do not own this page — stop donating
                break
            self._clock += 1  # graftlint: threadsafe (_locked helper: every caller holds self._lock)
            child = _Node(key, int(row[j]), node, self._clock)
            node.children[key] = child
            self._nodes.append(child)
            donated.append(int(row[j]))
            node = child
        tail = tuple(prompt[n_full * ps:])
        if tail and n_full >= shared_full:
            if tail not in node.children:
                self._clock += 1  # graftlint: threadsafe (_locked helper: every caller holds self._lock)
                child = _Node(tail, int(row[n_full]), node, self._clock)
                node.children[tail] = child
                self._nodes.append(child)
                donated.append(int(row[n_full]))
        return donated

    # -- queries (engine hot path + telemetry) ------------------------

    def tables(self):
        """Snapshot of the full page-table array (num_slots,
        pages_per_slot) int32 — what rides into the jitted decode step
        each iteration."""
        with self._lock:
            return self._tables.copy()

    def table_row(self, slot: int):
        with self._lock:
            return self._tables[slot].copy()

    def cached_pages(self) -> List[int]:
        """Physical pages currently owned by the radix tree (the
        ``prefix_corrupt`` fault poisons one of these)."""
        with self._lock:
            return [n.page for n in self._nodes]

    def stats(self) -> dict:
        with self._lock:
            return {
                "total": self.capacity,
                "free": len(self._free),
                "cached": len(self._nodes),
                "cow_forks_total": self._cow_forks,
                "hits_total": self._hits,
                "misses_total": self._misses,
                "evictions_total": self._evictions,
                "tier_hits_total": self._tier_hits,
                "page_size": self.page_size,
                "pages_per_slot": self.pages_per_slot,
            }
