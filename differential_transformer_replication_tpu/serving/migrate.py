"""Live decode-state migration: wire format + the router's replay journal.

Zero-loss in-flight failover rides two complementary mechanisms, both
grounded in the same determinism argument (the ``fold_in(PRNGKey(seed),
t)`` token key is a pure function of ``t``, so a continuation that
restores — or replays — the first ``t`` tokens continues the sampling
stream bit-exactly):

- **Migration** (drain path): one slot's full decode state — page-table
  row worth of live KV pages (int8 values + fp32 scale planes, captured
  with the same ``gather_slot_cache``-style reads the host tier uses),
  emitted tokens, constraint-FSM cursor, spec counters, priority class
  and remaining deadline — serialized by :func:`encode_slot_state` into
  a versioned, length-prefixed, per-page-CRC32 wire image and shipped to
  a peer replica, which re-admits it through the SAME zero-recompile
  swap-in machinery as host-tier resume (serving/engine.py:
  ``_try_resume``). Pages whose prompt-prefix the destination's radix
  tree already holds are NOT shipped (radix dedup — the destination
  copies them device-locally instead).
- **Replay** (crash path): when the source is already dead there is
  nothing to export; the router resubmits prompt+emitted-so-far as a
  prefill on a peer with ``SamplingParams.key_offset`` carrying the
  key-chain position, so the continuation's tokens are bit-identical
  without any page transfer. The emitted prefix comes from
  :class:`ReplayJournal`, the router's bounded per-inflight-request
  journal.

The fallback ladder is migrate -> replay -> plain retry; every rung is
typed and counted (``router_migrations_total{outcome=}``). A torn or
corrupted transfer is convicted by checksum HERE, at decode — garbage
KV can never be attended.

Checksums reuse serving/host_tier.py's canonical (layer, sorted-key)
CRC32 so a page image that round-trips through the tier and the wire
carries one consistent fingerprint.
"""

from __future__ import annotations

import base64
import json
import struct
import threading
from collections import OrderedDict
from dataclasses import asdict
from typing import List, Optional, Tuple

import numpy as np

from differential_transformer_replication_tpu.serving.host_tier import (
    payload_checksum,
)
from differential_transformer_replication_tpu.serving.request import (
    SamplingParams,
)

# Wire header: magic + version. Bump the version on ANY layout change —
# a mixed-version fleet mid-rolling-restart must fail typed (and fall
# back to replay), never misparse pages.
MIGRATE_MAGIC = b"DTXM"
MIGRATE_VERSION = 1

_HDR = struct.Struct(">4sHI")       # magic, version, meta length
_PAGE_HDR = struct.Struct(">BII")   # present flag, crc32, section length


class MigratePayloadError(ValueError):
    """A migration payload that cannot be trusted: torn framing, bad
    magic/version, or a page section whose CRC32 does not match. Typed
    so every caller (import endpoint, drain orchestration) can convict
    the transfer and fall back to replay — never inject garbage KV."""


class MigrateExportError(RuntimeError):
    """A migration that cannot proceed right now: contiguous KV layout
    (nothing page-shaped to ship), the request holds no ACTIVE slot
    (queued / prefilling / already finished), geometry mismatch between
    source and destination engines, or the dedup chain was evicted
    between probe and import. Typed with a machine-readable ``code`` so
    the drain orchestration picks the right fallback rung (replay ->
    plain retry) and counts it — never a wedge."""

    def __init__(self, msg: str, code: str = "migrate_unsupported"):
        super().__init__(msg)
        self.code = code


def params_to_dict(params: SamplingParams) -> dict:
    """SamplingParams -> JSON-safe dict (wire meta). Tuples become
    lists in transit; ``params_from_dict`` round-trips them through
    SamplingParams' own list->tuple normalization."""
    return asdict(params)


def params_from_dict(d: dict) -> SamplingParams:
    return SamplingParams(**d)


def _page_layout(payload) -> list:
    """Serializable (key, dtype, shape) descriptor per layer — the
    slicing recipe :func:`_unpack_page` rebuilds arrays with. Keys are
    sorted so the byte order matches ``payload_checksum``'s canonical
    walk exactly (one fingerprint across tier and wire)."""
    return [
        [
            [key, str(layer[key].dtype), list(layer[key].shape)]
            for key in sorted(layer)
        ]
        for layer in payload
    ]


def _pack_page(payload) -> bytes:
    return b"".join(
        np.ascontiguousarray(layer[key]).tobytes()
        for layer in payload
        for key in sorted(layer)
    )


def _unpack_page(data: bytes, layout: list) -> list:
    payload = []
    off = 0
    for layer_desc in layout:
        layer = {}
        for key, dtype, shape in layer_desc:
            n = int(np.dtype(dtype).itemsize * int(np.prod(shape)))
            chunk = data[off:off + n]
            if len(chunk) != n:
                raise MigratePayloadError(
                    f"torn page section: leaf {key!r} needs {n} bytes, "
                    f"got {len(chunk)}"
                )
            layer[key] = (
                np.frombuffer(chunk, dtype=np.dtype(dtype))
                .reshape(shape)
                .copy()  # owned + writable, like _extract_page's copies
            )
            off += n
        payload.append(layer)
    if off != len(data):
        raise MigratePayloadError(
            f"page section has {len(data) - off} trailing bytes"
        )
    return payload


def encode_slot_state(meta: dict,
                      payloads: List[Optional[list]]) -> bytes:
    """Serialize one slot's decode state. ``meta`` is a JSON-safe dict
    (prompt, params, generated tokens, FSM cursor, remaining deadline,
    geometry); ``payloads`` is the per-logical-page list of host page
    images (``_extract_page`` output) with ``None`` holes for pages the
    destination's radix tree already holds (dedup — not shipped)."""
    first = next((p for p in payloads if p is not None), None)
    meta = dict(meta)
    meta["page_layout"] = _page_layout(first) if first is not None else []
    meta_b = json.dumps(meta).encode("utf-8")
    parts = [_HDR.pack(MIGRATE_MAGIC, MIGRATE_VERSION, len(meta_b)), meta_b]
    parts.append(struct.pack(">I", len(payloads)))
    for payload in payloads:
        if payload is None:
            parts.append(_PAGE_HDR.pack(0, 0, 0))
            continue
        data = _pack_page(payload)
        parts.append(
            _PAGE_HDR.pack(1, payload_checksum(payload), len(data))
        )
        parts.append(data)
    return b"".join(parts)


def decode_slot_state(blob: bytes) -> Tuple[dict, List[Optional[list]]]:
    """Parse + VERIFY a wire image. Every page section's CRC32 is
    recomputed over the rebuilt arrays (the same canonical walk that
    stamped it) before anything reaches the device — a flipped byte
    anywhere in a shipped page raises :class:`MigratePayloadError`."""
    if len(blob) < _HDR.size:
        raise MigratePayloadError("torn header")
    magic, version, meta_len = _HDR.unpack_from(blob, 0)
    if magic != MIGRATE_MAGIC:
        raise MigratePayloadError(f"bad magic {magic!r}")
    if version != MIGRATE_VERSION:
        raise MigratePayloadError(
            f"wire version {version} != {MIGRATE_VERSION} (mixed-version "
            "fleet mid-rollout — fall back to replay)"
        )
    off = _HDR.size
    if off + meta_len + 4 > len(blob):
        raise MigratePayloadError("torn meta section")
    try:
        meta = json.loads(blob[off:off + meta_len].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise MigratePayloadError(f"unparseable meta: {e}") from e
    off += meta_len
    (n_pages,) = struct.unpack_from(">I", blob, off)
    off += 4
    layout = meta.get("page_layout") or []
    payloads: List[Optional[list]] = []
    for i in range(n_pages):
        if off + _PAGE_HDR.size > len(blob):
            raise MigratePayloadError(f"torn page {i} header")
        present, crc, n = _PAGE_HDR.unpack_from(blob, off)
        off += _PAGE_HDR.size
        if not present:
            payloads.append(None)
            continue
        if not layout:
            raise MigratePayloadError("shipped page but empty page_layout")
        data = blob[off:off + n]
        if len(data) != n:
            raise MigratePayloadError(
                f"torn page {i}: wanted {n} bytes, got {len(data)}"
            )
        off += n
        payload = _unpack_page(data, layout)
        if payload_checksum(payload) != crc:
            raise MigratePayloadError(
                f"page {i} checksum mismatch — corrupt transfer convicted"
            )
        payloads.append(payload)
    if off != len(blob):
        raise MigratePayloadError(
            f"{len(blob) - off} trailing bytes after page {n_pages - 1}"
        )
    return meta, payloads


def to_wire(blob: bytes) -> str:
    """Binary image -> JSON-safe transport string (base64)."""
    return base64.b64encode(blob).decode("ascii")


def from_wire(text: str) -> bytes:
    try:
        return base64.b64decode(text.encode("ascii"), validate=True)
    except Exception as e:
        raise MigratePayloadError(f"undecodable transport body: {e}") from e


class ReplayJournal:
    """Bounded per-inflight-request journal of emitted tokens.

    The router harvests each replica's ``GET /inflight`` snapshot into
    this journal; on a retriable replica death it replays prompt +
    journaled tokens on a peer (``key_offset`` = journal length).
    Correctness needs only a PREFIX of the truly-emitted tokens —
    harvest lag just means a few tokens are re-generated bit-exactly —
    so updates may lag and entries may be truncated by the per-request
    cap without ever producing a wrong continuation.

    Bounded two ways: ``max_tokens`` caps each entry (a runaway
    generation cannot balloon the journal — the entry stops growing and
    replay degrades gracefully to a longer re-decode), and finished
    entries ride an LRU of ``max_finished`` so post-finish stragglers
    (late duplicate replies) still resolve without unbounded growth.
    ``router_replay_journal_bytes`` mirrors :meth:`stats`.
    """

    _TOKEN_BYTES = 4  # int32-equivalent accounting per journaled token

    def __init__(self, max_tokens: int = 4096,
                 max_finished: int = 1024) -> None:
        self.max_tokens = int(max_tokens)
        self.max_finished = int(max_finished)
        self._lock = threading.Lock()
        self._live: "OrderedDict[str, list]" = OrderedDict()
        self._finished: "OrderedDict[str, int]" = OrderedDict()
        self._bytes = 0
        self._evicted = 0

    def begin(self, journal_id: str) -> None:
        """Register an in-flight request (idempotent)."""
        with self._lock:
            if journal_id not in self._live:
                self._live[journal_id] = []

    def update(self, journal_id: str, tokens: List[int]) -> None:
        """Extend a live entry to the harvested emitted-token prefix.
        Only ever GROWS an entry (a stale probe body cannot shrink the
        journal below what a fresher one recorded) and never past the
        per-request cap."""
        with self._lock:
            cur = self._live.get(journal_id)
            if cur is None or len(tokens) <= len(cur):
                return
            grown = [int(t) for t in tokens[:self.max_tokens]]
            if len(grown) > len(cur):
                self._bytes += (len(grown) - len(cur)) * self._TOKEN_BYTES
                self._live[journal_id] = grown

    def tokens(self, journal_id: str) -> Optional[List[int]]:
        """The journaled emitted-token prefix (a copy), or None when the
        request was never registered (plain retry is the only rung)."""
        with self._lock:
            cur = self._live.get(journal_id)
            return list(cur) if cur is not None else None

    def finish(self, journal_id: str) -> None:
        """Retire an entry: its token bytes are released and the id
        moves to the finished LRU (late duplicate replies resolve as
        finished instead of re-registering)."""
        with self._lock:
            cur = self._live.pop(journal_id, None)
            if cur is not None:
                self._bytes -= len(cur) * self._TOKEN_BYTES
            self._finished[journal_id] = 1
            self._finished.move_to_end(journal_id)
            while len(self._finished) > self.max_finished:
                self._finished.popitem(last=False)
                self._evicted += 1

    def finished(self, journal_id: str) -> bool:
        with self._lock:
            return journal_id in self._finished

    def stats(self) -> dict:
        with self._lock:
            return {
                "bytes": self._bytes,
                "entries": len(self._live),
                "finished": len(self._finished),
                "evicted_total": self._evicted,
            }
