"""Continuous-batching inference serving (see serving/engine.py)."""

from differential_transformer_replication_tpu.serving.engine import (
    EngineCrashError,
    ServingEngine,
)
from differential_transformer_replication_tpu.serving.request import (
    Request,
    RequestOutput,
    SamplingParams,
)
from differential_transformer_replication_tpu.serving.retry import (
    backoff_delay,
    call_with_retries,
    http_post_json_with_retries,
)
from differential_transformer_replication_tpu.serving.scheduler import (
    DeadlineExceededError,
    QueueFullError,
    Scheduler,
)
from differential_transformer_replication_tpu.serving.server import (
    EngineRunner,
    ServingClient,
    ShuttingDownError,
    serve,
)

__all__ = [
    "ServingEngine",
    "EngineCrashError",
    "Request",
    "RequestOutput",
    "SamplingParams",
    "Scheduler",
    "QueueFullError",
    "DeadlineExceededError",
    "ShuttingDownError",
    "EngineRunner",
    "ServingClient",
    "serve",
    "backoff_delay",
    "call_with_retries",
    "http_post_json_with_retries",
]
