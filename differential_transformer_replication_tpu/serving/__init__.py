"""Continuous-batching inference serving (see serving/engine.py)."""

from differential_transformer_replication_tpu.serving.engine import (
    ServingEngine,
)
from differential_transformer_replication_tpu.serving.request import (
    Request,
    RequestOutput,
    SamplingParams,
)
from differential_transformer_replication_tpu.serving.scheduler import (
    QueueFullError,
    Scheduler,
)
from differential_transformer_replication_tpu.serving.server import (
    EngineRunner,
    ServingClient,
    serve,
)

__all__ = [
    "ServingEngine",
    "Request",
    "RequestOutput",
    "SamplingParams",
    "Scheduler",
    "QueueFullError",
    "EngineRunner",
    "ServingClient",
    "serve",
]
