"""Continuous-batching inference serving (see serving/engine.py).

Exports resolve lazily (PEP 562): the engine/server stack pulls in jax,
but the host-side members of this package — :mod:`serving.retry` and
:mod:`serving.router` — are pure stdlib and must stay importable from
processes that deliberately avoid the device runtime (the fleet
launcher and router front, tools/fleet.py, which babysit the very
processes whose runtime may be crashing). ``from ...serving import
ServingEngine`` works exactly as before; it just pays the jax import at
first attribute access instead of at package import.
"""

from typing import TYPE_CHECKING

# attribute name -> submodule that defines it
_EXPORTS = {
    "ServingEngine": "engine",
    "EngineCrashError": "engine",
    "ConstraintCache": "constrain",
    "ConstraintCompileError": "constrain",
    "ConstraintDeadEndError": "constrain",
    "TokenFsm": "constrain",
    "HostTier": "host_tier",
    "Request": "request",
    "RequestOutput": "request",
    "SamplingParams": "request",
    "Scheduler": "scheduler",
    "PagePool": "pages",
    "PagePoolExhaustedError": "pages",
    "ModelDrafter": "spec",
    "NGramDrafter": "spec",
    "QueueFullError": "scheduler",
    "DeadlineExceededError": "scheduler",
    "ShuttingDownError": "server",
    "EngineRunner": "server",
    "ServingClient": "server",
    "serve": "server",
    "backoff_delay": "retry",
    "call_with_retries": "retry",
    "http_post_json_with_retries": "retry",
    "Router": "router",
    "Replica": "router",
    "serve_router": "router",
}

__all__ = sorted(_EXPORTS)

if TYPE_CHECKING:  # static analyzers see the eager imports
    from differential_transformer_replication_tpu.serving.constrain import (
        ConstraintCache,
        ConstraintCompileError,
        ConstraintDeadEndError,
        TokenFsm,
    )
    from differential_transformer_replication_tpu.serving.engine import (
        EngineCrashError,
        ServingEngine,
    )
    from differential_transformer_replication_tpu.serving.host_tier import (
        HostTier,
    )
    from differential_transformer_replication_tpu.serving.pages import (
        PagePool,
        PagePoolExhaustedError,
    )
    from differential_transformer_replication_tpu.serving.request import (
        Request,
        RequestOutput,
        SamplingParams,
    )
    from differential_transformer_replication_tpu.serving.retry import (
        backoff_delay,
        call_with_retries,
        http_post_json_with_retries,
    )
    from differential_transformer_replication_tpu.serving.router import (
        Replica,
        Router,
        serve_router,
    )
    from differential_transformer_replication_tpu.serving.scheduler import (
        DeadlineExceededError,
        QueueFullError,
        Scheduler,
    )
    from differential_transformer_replication_tpu.serving.server import (
        EngineRunner,
        ServingClient,
        ShuttingDownError,
        serve,
    )
    from differential_transformer_replication_tpu.serving.spec import (
        ModelDrafter,
        NGramDrafter,
    )


def __getattr__(name: str):
    submodule = _EXPORTS.get(name)
    if submodule is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        )
    import importlib

    module = importlib.import_module(f"{__name__}.{submodule}")
    value = getattr(module, name)
    globals()[name] = value  # cache: resolve each name once
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
