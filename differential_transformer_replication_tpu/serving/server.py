"""Minimal serving front-ends over the continuous-batching engine.

Three layers, smallest useful surface each:

- :class:`EngineRunner` — a background thread that owns a
  :class:`ServingEngine` (which is not thread-safe) and drains it:
  concurrent callers enqueue requests through a lock, the loop moves
  them into the engine and steps until idle, then parks on a condition
  variable. This is the concurrency boundary — everything device-side
  stays single-threaded.
- :class:`ServingClient` — the programmatic client tests and the bench
  use: blocking ``generate()`` per caller thread, n callers = n
  concurrent streams batched by the engine. Runs fully in-process under
  ``JAX_PLATFORMS=cpu``.
- :func:`serve` / ``python -m ...serving.server`` — a stdlib
  ``http.server`` JSON endpoint (no new dependencies): POST /generate
  with ``{"prompt_ids": [...]}`` (or ``{"prompt": "text"}`` when a
  tokenizer dir is given), GET /health for engine stats. One engine,
  many HTTP threads, continuous batching across them.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Sequence

from differential_transformer_replication_tpu.serving.engine import (
    ServingEngine,
)
from differential_transformer_replication_tpu.serving.request import (
    RequestOutput,
    SamplingParams,
)
from differential_transformer_replication_tpu.serving.scheduler import (
    QueueFullError,
)


class _Pending:
    """One submitted request's handle across the thread boundary."""

    __slots__ = ("prompt", "params", "done", "result", "error", "rid",
                 "cancelled")

    def __init__(self, prompt, params):
        self.prompt = prompt
        self.params = params
        self.done = threading.Event()
        self.result: Optional[RequestOutput] = None
        self.error: Optional[BaseException] = None
        self.rid: Optional[int] = None  # set once the engine admits it
        self.cancelled = False

    def fail(self, e: BaseException) -> None:
        self.error = e
        self.done.set()


class EngineRunner:
    """Owns the engine on a background thread; see module docstring."""

    def __init__(self, engine: ServingEngine):
        self.engine = engine
        self._cond = threading.Condition()
        self._incoming: deque = deque()  # _Pending not yet in the engine
        self._cancels: deque = deque()  # _Pending to cancel in the engine
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, name="serving-engine", daemon=True
        )
        self._thread.start()

    def submit(self, prompt: Sequence[int],
               params: Optional[SamplingParams] = None, **kw) -> _Pending:
        """Thread-safe enqueue; returns the request's :class:`_Pending`
        handle. Raises :class:`QueueFullError` IMMEDIATELY when the
        admission bound (ServingConfig.max_queue_len) is hit — counting
        both the engine's wait queue and requests still in this runner's
        hand-off deque — so overload degrades into fast rejections the
        caller can act on."""
        params = params or SamplingParams(**kw)
        pending = _Pending(list(prompt), params)
        with self._cond:
            if self._stop:
                raise RuntimeError("EngineRunner is closed")
            maxq = self.engine.serving.max_queue_len
            # cancelled-but-undrained pendings no longer occupy the wait
            # queue they are counted against — a burst of client
            # timeouts must not cause spurious 503s for the next caller
            waiting = sum(1 for p in self._incoming if not p.cancelled)
            if maxq and waiting + self.engine.queue_len() >= maxq:
                self.engine.stats["rejected"] += 1
                raise QueueFullError(
                    f"admission queue full ({maxq} waiting); retry later"
                )
            self._incoming.append(pending)
            self._cond.notify()
        return pending

    def cancel(self, pending: _Pending) -> None:
        """Abandon a request: if still in the hand-off deque it is
        dropped before ever reaching the engine; if already admitted,
        the engine reclaims its queue entry / KV slot on the next loop
        pass (serving/engine.py:cancel). Safe to call concurrently with
        completion — a request that finished first just ignores it."""
        with self._cond:
            pending.cancelled = True
            self._cancels.append(pending)
            self._cond.notify()

    def generate(self, prompt: Sequence[int],
                 params: Optional[SamplingParams] = None,
                 timeout: Optional[float] = None, **kw) -> RequestOutput:
        pending = self.submit(prompt, params, **kw)
        if not pending.done.wait(timeout):
            # reclaim the engine-side resources before giving up — the
            # old behavior decoded to completion for nobody, pinning a
            # KV slot other callers were queued for
            self.cancel(pending)
            raise TimeoutError("generation timed out")
        if pending.error is not None:
            raise pending.error
        return pending.result

    def close(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify()
        self._thread.join(timeout=30)

    def _loop(self) -> None:
        waiters: dict = {}  # request_id -> _Pending
        while True:
            with self._cond:
                while (
                    not self._incoming
                    and not self._cancels
                    and not self.engine.has_work()
                ):
                    if self._stop:
                        return
                    self._cond.wait()
                incoming = list(self._incoming)
                self._incoming.clear()
                cancels = list(self._cancels)
                self._cancels.clear()
                stopping = self._stop
            for pending in cancels:
                if pending.rid is not None:
                    if self.engine.cancel(pending.rid):
                        waiters.pop(pending.rid, None)
                # rid None: either still in `incoming` (skipped below) or
                # it finished before the cancel landed — nothing to undo
            for pending in incoming:
                if pending.cancelled:
                    continue
                try:
                    pending.rid = self.engine.submit(
                        pending.prompt, params=pending.params
                    )
                    waiters[pending.rid] = pending
                except Exception as e:  # invalid request: fail the caller
                    pending.fail(e)
            try:
                for out in self.engine.step():
                    pending = waiters.pop(out.request_id)
                    pending.result = out
                    pending.done.set()
            except Exception as e:
                # a device-side failure (OOM, runtime error) must not
                # strand callers on a dead thread: fail every waiter and
                # refuse further work
                for pending in waiters.values():
                    pending.fail(e)
                with self._cond:
                    self._stop = True
                    for pending in self._incoming:
                        pending.fail(e)
                    self._incoming.clear()
                raise
            if stopping and not self.engine.has_work():
                return


class ServingClient:
    """In-process client: one engine, blocking calls from any thread."""

    def __init__(self, engine: ServingEngine):
        self.runner = EngineRunner(engine)

    def generate(self, prompt: Sequence[int],
                 params: Optional[SamplingParams] = None,
                 timeout: Optional[float] = None, **kw) -> RequestOutput:
        return self.runner.generate(prompt, params, timeout=timeout, **kw)

    def generate_batch(self, prompts: Sequence[Sequence[int]],
                       params: Optional[Sequence[SamplingParams]] = None,
                       timeout: Optional[float] = None,
                       **kw) -> List[RequestOutput]:
        """Submit all prompts, then wait — batched by the engine. A
        timeout cancels every still-unfinished request in the batch
        before raising (no orphaned decodes)."""
        shared = SamplingParams(**kw) if params is None else None
        handles = []
        try:
            for i, p in enumerate(prompts):
                handles.append(
                    self.runner.submit(p, shared if shared else params[i])
                )
        except Exception:
            # a mid-batch rejection (QueueFullError, closed runner) must
            # not orphan the prompts already accepted
            for h in handles:
                if not h.done.is_set():
                    self.runner.cancel(h)
            raise
        outs = []
        for pending in handles:
            ok = pending.done.wait(timeout)
            if not ok or pending.error is not None:
                # timeout OR one request failing: reclaim every still-
                # running sibling before raising — nothing may keep
                # decoding for a caller that is about to unwind
                for h in handles:
                    if not h.done.is_set():
                        self.runner.cancel(h)
                if not ok:
                    raise TimeoutError("generation timed out")
                raise pending.error
            outs.append(pending.result)
        return outs

    @property
    def stats(self) -> dict:
        return dict(self.runner.engine.stats)

    def close(self) -> None:
        self.runner.close()


def _make_handler(client: ServingClient, tokenizer=None):
    class Handler(BaseHTTPRequestHandler):
        def _reply(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/health":
                self._reply(200, {"ok": True, "stats": client.stats})
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            if self.path != "/generate":
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(n) or b"{}")
                prompt_ids = req.get("prompt_ids")
                if prompt_ids is None and "prompt" in req:
                    if tokenizer is None:
                        raise ValueError(
                            "text prompts need the server started with a "
                            "tokenizer dir; send prompt_ids instead"
                        )
                    prompt_ids = tokenizer.encode(req["prompt"]).ids
                if not prompt_ids:
                    raise ValueError("prompt_ids (or prompt) required")
                top_k = req.get("top_k")
                eos = req.get("eos_token_id")
                params = SamplingParams(
                    max_new_tokens=int(req.get("max_new_tokens", 16)),
                    temperature=float(req.get("temperature", 1.0)),
                    top_k=None if top_k is None else int(top_k),
                    seed=int(req.get("seed", 0)),
                    eos_token_id=None if eos is None else int(eos),
                )
                out = client.generate(
                    [int(t) for t in prompt_ids], params,
                    timeout=float(req.get("timeout", 600.0)),
                )
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                self._reply(400, {"error": str(e)})
                return
            except QueueFullError as e:
                # overload: reject fast with the retryable status so
                # load balancers/clients back off instead of piling on
                self._reply(503, {"error": f"server overloaded: {e}"})
                return
            except TimeoutError:
                self._reply(503, {"error": "generation timed out"})
                return
            except RuntimeError as e:  # runner closed / engine failure
                self._reply(500, {"error": str(e)})
                return
            payload = {
                "request_id": out.request_id,
                "prompt_ids": out.prompt,
                "tokens": out.tokens,
                "finish_reason": out.finish_reason,
                "ttft_ms": round(out.ttft * 1e3, 3),
            }
            if tokenizer is not None:
                payload["text"] = tokenizer.decode(out.tokens)
            self._reply(200, payload)

        def log_message(self, *a):  # quiet by default
            pass

    return Handler


def serve(client: ServingClient, host: str = "127.0.0.1", port: int = 8000,
          tokenizer=None) -> ThreadingHTTPServer:
    """Build the HTTP server (not yet serving; call serve_forever())."""
    return ThreadingHTTPServer(
        (host, port), _make_handler(client, tokenizer)
    )


def main() -> None:
    """CLI: serve a checkpoint (or a random-init demo model) over HTTP."""
    import argparse

    import jax

    from differential_transformer_replication_tpu.config import (
        ModelConfig,
        ServingConfig,
    )

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--checkpoint", default=None,
                   help="training checkpoint dir (meta.json + "
                        "state.msgpack); omit for a random-init demo model")
    p.add_argument("--tokenizer", default=None,
                   help="tokenizer dir enabling text prompts "
                        "(vocab.json + merges.txt)")
    p.add_argument("--model", default="control",
                   help="demo model family when no checkpoint is given")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--num-slots", type=int, default=8)
    p.add_argument("--prefill-chunk", type=int, default=128)
    p.add_argument("--prefill-budget", type=int, default=256)
    p.add_argument("--max-seq-len", type=int, default=0)
    p.add_argument("--max-queue-len", type=int, default=0,
                   help="reject (HTTP 503) submissions past this many "
                        "waiting requests; 0 = unbounded")
    args = p.parse_args()

    meta = None
    if args.checkpoint:
        from differential_transformer_replication_tpu.train.checkpoint import (
            load_params_for_inference,
        )

        params, model_cfg, meta = load_params_for_inference(args.checkpoint)
    else:
        from differential_transformer_replication_tpu.models import init_model

        model_cfg = ModelConfig(
            model=args.model, vocab_size=512, n_embd=64, n_head=2,
            n_layer=2, block_size=128, compute_dtype="float32",
        )
        params = init_model(jax.random.PRNGKey(0), model_cfg)
        print("[serve] no checkpoint given: random-init demo model")

    tokenizer = None
    if args.tokenizer:
        from differential_transformer_replication_tpu.data.tokenizer import (
            check_tokenizer_matches,
            load_tokenizer,
        )

        tokenizer = load_tokenizer(args.tokenizer)
        if meta is not None:
            # refuse to serve text through a tokenizer that cannot belong
            # to the checkpoint (same guard as sample.py — a clobbered
            # shared tokenizer dir would silently emit garbage text)
            check_tokenizer_matches(
                tokenizer, model_cfg.vocab_size,
                meta.get("tokenizer_fingerprint"), context=args.checkpoint,
            )

    serving = ServingConfig(
        num_slots=args.num_slots, prefill_chunk=args.prefill_chunk,
        prefill_budget=args.prefill_budget, max_seq_len=args.max_seq_len,
        max_queue_len=args.max_queue_len,
    )
    client = ServingClient(ServingEngine(params, model_cfg, serving))
    httpd = serve(client, args.host, args.port, tokenizer)
    print(
        f"[serve] {model_cfg.model} model, {serving.num_slots} slots — "
        f"POST http://{args.host}:{args.port}/generate"
    )
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        client.close()


if __name__ == "__main__":
    main()
