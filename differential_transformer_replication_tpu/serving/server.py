"""Minimal serving front-ends over the continuous-batching engine.

Three layers, smallest useful surface each:

- :class:`EngineRunner` — a background thread that owns a
  :class:`ServingEngine` (which is not thread-safe) and drains it:
  concurrent callers enqueue requests through a lock, the loop moves
  them into the engine and steps until idle, then parks on a condition
  variable. This is the concurrency boundary — everything device-side
  stays single-threaded.
- :class:`ServingClient` — the programmatic client tests and the bench
  use: blocking ``generate()`` per caller thread, n callers = n
  concurrent streams batched by the engine. Runs fully in-process under
  ``JAX_PLATFORMS=cpu``.
- :func:`serve` / ``python -m ...serving.server`` — a stdlib
  ``http.server`` JSON endpoint (no new dependencies): POST /generate
  with ``{"prompt_ids": [...]}`` (or ``{"prompt": "text"}`` when a
  tokenizer dir is given), GET /health for engine stats. One engine,
  many HTTP threads, continuous batching across them.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Sequence

from differential_transformer_replication_tpu.serving.engine import (
    ServingEngine,
)
from differential_transformer_replication_tpu.serving.request import (
    RequestOutput,
    SamplingParams,
)


class EngineRunner:
    """Owns the engine on a background thread; see module docstring."""

    def __init__(self, engine: ServingEngine):
        self.engine = engine
        self._cond = threading.Condition()
        self._incoming: deque = deque()  # (prompt, params, done Event, box)
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, name="serving-engine", daemon=True
        )
        self._thread.start()

    def submit(self, prompt: Sequence[int],
               params: Optional[SamplingParams] = None, **kw):
        """Thread-safe enqueue; returns (event, box) — ``box[0]`` holds
        the RequestOutput (or ``box[1]`` an exception) once set."""
        params = params or SamplingParams(**kw)
        done = threading.Event()
        box: list = [None, None]
        with self._cond:
            if self._stop:
                raise RuntimeError("EngineRunner is closed")
            self._incoming.append((list(prompt), params, done, box))
            self._cond.notify()
        return done, box

    def generate(self, prompt: Sequence[int],
                 params: Optional[SamplingParams] = None,
                 timeout: Optional[float] = None, **kw) -> RequestOutput:
        done, box = self.submit(prompt, params, **kw)
        if not done.wait(timeout):
            raise TimeoutError("generation timed out")
        if box[1] is not None:
            raise box[1]
        return box[0]

    def close(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify()
        self._thread.join(timeout=30)

    def _loop(self) -> None:
        waiters: dict = {}  # request_id -> (Event, box)
        while True:
            with self._cond:
                while not self._incoming and not self.engine.has_work():
                    if self._stop:
                        return
                    self._cond.wait()
                incoming = list(self._incoming)
                self._incoming.clear()
                stopping = self._stop
            for prompt, params, done, box in incoming:
                try:
                    rid = self.engine.submit(prompt, params=params)
                    waiters[rid] = (done, box)
                except Exception as e:  # invalid request: fail the caller
                    box[1] = e
                    done.set()
            try:
                for out in self.engine.step():
                    done, box = waiters.pop(out.request_id)
                    box[0] = out
                    done.set()
            except Exception as e:
                # a device-side failure (OOM, runtime error) must not
                # strand callers on a dead thread: fail every waiter and
                # refuse further work
                for done, box in waiters.values():
                    box[1] = e
                    done.set()
                with self._cond:
                    self._stop = True
                    for _, _, done, box in self._incoming:
                        box[1] = e
                        done.set()
                    self._incoming.clear()
                raise
            if stopping and not self.engine.has_work():
                return


class ServingClient:
    """In-process client: one engine, blocking calls from any thread."""

    def __init__(self, engine: ServingEngine):
        self.runner = EngineRunner(engine)

    def generate(self, prompt: Sequence[int],
                 params: Optional[SamplingParams] = None,
                 timeout: Optional[float] = None, **kw) -> RequestOutput:
        return self.runner.generate(prompt, params, timeout=timeout, **kw)

    def generate_batch(self, prompts: Sequence[Sequence[int]],
                       params: Optional[Sequence[SamplingParams]] = None,
                       timeout: Optional[float] = None,
                       **kw) -> List[RequestOutput]:
        """Submit all prompts, then wait — batched by the engine."""
        shared = SamplingParams(**kw) if params is None else None
        handles = [
            self.runner.submit(p, shared if shared else params[i])
            for i, p in enumerate(prompts)
        ]
        outs = []
        for done, box in handles:
            if not done.wait(timeout):
                raise TimeoutError("generation timed out")
            if box[1] is not None:
                raise box[1]
            outs.append(box[0])
        return outs

    @property
    def stats(self) -> dict:
        return dict(self.runner.engine.stats)

    def close(self) -> None:
        self.runner.close()


def _make_handler(client: ServingClient, tokenizer=None):
    class Handler(BaseHTTPRequestHandler):
        def _reply(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/health":
                self._reply(200, {"ok": True, "stats": client.stats})
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):
            if self.path != "/generate":
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(n) or b"{}")
                prompt_ids = req.get("prompt_ids")
                if prompt_ids is None and "prompt" in req:
                    if tokenizer is None:
                        raise ValueError(
                            "text prompts need the server started with a "
                            "tokenizer dir; send prompt_ids instead"
                        )
                    prompt_ids = tokenizer.encode(req["prompt"]).ids
                if not prompt_ids:
                    raise ValueError("prompt_ids (or prompt) required")
                top_k = req.get("top_k")
                eos = req.get("eos_token_id")
                params = SamplingParams(
                    max_new_tokens=int(req.get("max_new_tokens", 16)),
                    temperature=float(req.get("temperature", 1.0)),
                    top_k=None if top_k is None else int(top_k),
                    seed=int(req.get("seed", 0)),
                    eos_token_id=None if eos is None else int(eos),
                )
                out = client.generate(
                    [int(t) for t in prompt_ids], params,
                    timeout=float(req.get("timeout", 600.0)),
                )
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                self._reply(400, {"error": str(e)})
                return
            except TimeoutError:
                self._reply(503, {"error": "generation timed out"})
                return
            except RuntimeError as e:  # runner closed / engine failure
                self._reply(500, {"error": str(e)})
                return
            payload = {
                "request_id": out.request_id,
                "prompt_ids": out.prompt,
                "tokens": out.tokens,
                "finish_reason": out.finish_reason,
                "ttft_ms": round(out.ttft * 1e3, 3),
            }
            if tokenizer is not None:
                payload["text"] = tokenizer.decode(out.tokens)
            self._reply(200, payload)

        def log_message(self, *a):  # quiet by default
            pass

    return Handler


def serve(client: ServingClient, host: str = "127.0.0.1", port: int = 8000,
          tokenizer=None) -> ThreadingHTTPServer:
    """Build the HTTP server (not yet serving; call serve_forever())."""
    return ThreadingHTTPServer(
        (host, port), _make_handler(client, tokenizer)
    )


def main() -> None:
    """CLI: serve a checkpoint (or a random-init demo model) over HTTP."""
    import argparse

    import jax

    from differential_transformer_replication_tpu.config import (
        ModelConfig,
        ServingConfig,
    )

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--checkpoint", default=None,
                   help="training checkpoint dir (meta.json + "
                        "state.msgpack); omit for a random-init demo model")
    p.add_argument("--tokenizer", default=None,
                   help="tokenizer dir enabling text prompts "
                        "(vocab.json + merges.txt)")
    p.add_argument("--model", default="control",
                   help="demo model family when no checkpoint is given")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--num-slots", type=int, default=8)
    p.add_argument("--prefill-chunk", type=int, default=128)
    p.add_argument("--prefill-budget", type=int, default=256)
    p.add_argument("--max-seq-len", type=int, default=0)
    args = p.parse_args()

    meta = None
    if args.checkpoint:
        from differential_transformer_replication_tpu.train.checkpoint import (
            load_params_for_inference,
        )

        params, model_cfg, meta = load_params_for_inference(args.checkpoint)
    else:
        from differential_transformer_replication_tpu.models import init_model

        model_cfg = ModelConfig(
            model=args.model, vocab_size=512, n_embd=64, n_head=2,
            n_layer=2, block_size=128, compute_dtype="float32",
        )
        params = init_model(jax.random.PRNGKey(0), model_cfg)
        print("[serve] no checkpoint given: random-init demo model")

    tokenizer = None
    if args.tokenizer:
        from differential_transformer_replication_tpu.data.tokenizer import (
            check_tokenizer_matches,
            load_tokenizer,
        )

        tokenizer = load_tokenizer(args.tokenizer)
        if meta is not None:
            # refuse to serve text through a tokenizer that cannot belong
            # to the checkpoint (same guard as sample.py — a clobbered
            # shared tokenizer dir would silently emit garbage text)
            check_tokenizer_matches(
                tokenizer, model_cfg.vocab_size,
                meta.get("tokenizer_fingerprint"), context=args.checkpoint,
            )

    serving = ServingConfig(
        num_slots=args.num_slots, prefill_chunk=args.prefill_chunk,
        prefill_budget=args.prefill_budget, max_seq_len=args.max_seq_len,
    )
    client = ServingClient(ServingEngine(params, model_cfg, serving))
    httpd = serve(client, args.host, args.port, tokenizer)
    print(
        f"[serve] {model_cfg.model} model, {serving.num_slots} slots — "
        f"POST http://{args.host}:{args.port}/generate"
    )
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        client.close()


if __name__ == "__main__":
    main()
