"""Minimal serving front-ends over the continuous-batching engine.

Three layers, smallest useful surface each:

- :class:`EngineRunner` — a background thread that owns a
  :class:`ServingEngine` (which is not thread-safe) and drains it:
  concurrent callers enqueue requests through a lock, the loop moves
  them into the engine and steps until idle, then parks on a condition
  variable. This is the concurrency boundary — everything device-side
  stays single-threaded. It is also the SUPERVISOR (the serving-side
  analog of tools/train_supervisor.py): a crashed engine step fails the
  in-flight requests with a typed, retriable
  :class:`~.engine.EngineCrashError`, rebuilds the slot pool from
  params after a bounded exponential backoff, and keeps serving — wait-
  queue entries survive the restart verbatim. A wall-time watchdog
  flags iterations that exceed ``ServingConfig.step_time_budget_s``;
  :meth:`EngineRunner.status` reports
  ``healthy | degraded | restarting | draining | failed``.
- :class:`ServingClient` — the programmatic client tests and the bench
  use: blocking ``generate()`` per caller thread, n callers = n
  concurrent streams batched by the engine. Runs fully in-process under
  ``JAX_PLATFORMS=cpu``.
- :func:`serve` / ``python -m ...serving.server`` — a stdlib
  ``http.server`` JSON endpoint (no new dependencies): POST /generate
  with ``{"prompt_ids": [...]}`` (or ``{"prompt": "text"}`` when a
  tokenizer dir is given), GET /health for engine state + stats, GET
  /ready for load-balancer admission (503 + Retry-After while draining
  or restarting). SIGTERM triggers a graceful drain: admission stops
  (503 + Retry-After), in-flight requests finish within
  ``ServingConfig.drain_timeout_s``, then the process exits.

Live migration (serving/migrate.py) rides four extra endpoints: the
router drains a replica by enumerating ``GET /inflight`` and POSTing
``/migrate/export {request_id, dest, migrate_id}`` per active request —
the source then probes the destination's radix tree (``/migrate/probe``,
dedup), exports the slot's checksummed wire image, lands it with
``POST dest /migrate/import``, releases the slot, and answers the
original blocked ``/generate`` with ``200 {"code": "migrated"}`` so the
router re-issues ``POST dest /migrate/await {migrate_id}`` and returns
the COMPLETE token list from the peer. Only the device touches (export
snapshot, release) run as engine-thread commands between steps; the
network legs (probe, transfer) stay on the HTTP handler thread, so a
slow destination never stalls co-resident decodes — the slot keeps
decoding between snapshot and release, and the destination regenerates
any post-snapshot tokens bit-exactly (the key chain is pure in ``t``).
"""

from __future__ import annotations

import json
import sys
import threading
import time
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Sequence

from differential_transformer_replication_tpu.obs.events import (
    NOOP_EVENTS,
)
from differential_transformer_replication_tpu.obs.registry import (
    CONTENT_TYPE as METRICS_CONTENT_TYPE,
)
from differential_transformer_replication_tpu.obs.trace import (
    from_payload as trace_from_payload,
)
from differential_transformer_replication_tpu.serving.constrain import (
    ConstraintCompileError,
    ConstraintDeadEndError,
)
from differential_transformer_replication_tpu.serving.engine import (
    EngineCrashError,
    ServingEngine,
)
from differential_transformer_replication_tpu.serving.migrate import (
    MigrateExportError,
    MigratePayloadError,
    from_wire,
    to_wire,
)
from differential_transformer_replication_tpu.serving.pages import (
    PagePoolExhaustedError,
)
from differential_transformer_replication_tpu.serving.request import (
    RequestOutput,
    SamplingParams,
)
from differential_transformer_replication_tpu.serving.retry import (
    http_post_json_with_retries,
)
from differential_transformer_replication_tpu.serving.scheduler import (
    DeadlineExceededError,
    QueueFullError,
)


class ShuttingDownError(RuntimeError):
    """Admission refused: the server is draining (or already stopped).
    Retriable — against ANOTHER replica; HTTP maps it to 503 with a
    Retry-After so load balancers take the instance out of rotation."""

    retriable = True


class MigratedError(RuntimeError):
    """Settle marker, not a failure: this request's live decode state
    moved to a peer replica mid-flight (serving/migrate.py). The HTTP
    handler maps it to 200 ``{"code": "migrated", "dest", "migrate_id"}``
    so the router follows with ``POST dest /migrate/await`` and returns
    the peer's COMPLETE continuation to the caller."""

    def __init__(self, dest: str, migrate_id: str):
        super().__init__(f"request migrated to {dest}")
        self.dest = dest
        self.migrate_id = migrate_id


def _inc_stat(stats, key: str) -> None:
    """Bump one engine stat from outside the engine thread. Real engines
    carry a StatsMap whose ``inc`` is atomic (obs/registry.py); test
    doubles with plain dicts fall back to ``+=`` (their callers hold the
    runner lock, so the read-modify-write cannot tear)."""
    inc = getattr(stats, "inc", None)
    if inc is not None:
        inc(key)
    else:
        stats[key] += 1


class _Pending:
    """One submitted request's handle across the thread boundary."""

    __slots__ = ("prompt", "params", "deadline", "trace", "done",
                 "result", "error", "rid", "cancelled", "settled",
                 "journal_id")

    def __init__(self, prompt, params, deadline=None, trace=None,
                 journal_id=None):
        self.prompt = prompt
        self.params = params
        self.deadline = deadline  # absolute perf_counter ts, or None
        self.trace = trace        # TraceContext (obs/trace.py) or None
        self.done = threading.Event()
        self.result: Optional[RequestOutput] = None
        self.error: Optional[BaseException] = None
        self.rid: Optional[int] = None  # set once the engine admits it
        self.cancelled = False
        self.settled = False  # exactly-once delivery (drain accounting)
        # the router's replay-journal handle (serving/migrate.py):
        # echoed in GET /inflight so harvested token prefixes land in
        # the right journal entry
        self.journal_id = journal_id


class EngineRunner:
    """Owns + supervises the engine on a background thread; see module
    docstring. Supervision knobs come from the engine's
    ``ServingConfig``: ``max_restarts`` / ``restart_backoff_s`` /
    ``restart_backoff_max_s`` (crash recovery), ``step_time_budget_s``
    (watchdog), ``drain_timeout_s`` (graceful drain)."""

    def __init__(self, engine: ServingEngine):
        self.engine = engine
        serving = engine.serving
        self.max_restarts = serving.max_restarts
        self._backoff_base = serving.restart_backoff_s
        self._backoff_max = serving.restart_backoff_max_s
        self._step_budget = serving.step_time_budget_s
        self._cond = threading.Condition()
        self._incoming: deque = deque()  # _Pending not yet in the engine
        self._cancels: deque = deque()  # _Pending to cancel in the engine
        # engine-thread command queue (serving/migrate.py): migration
        # export/import thunks run here between steps, so a decode
        # iteration can never interleave with a half-exported slot
        self._commands: deque = deque()
        self._waiters: dict = {}  # request_id -> _Pending (engine thread)
        self._inflight: list = []  # last step's progress snapshot
        # migrate_id -> _Pending for imported requests; /migrate/await
        # blocks on these. Bounded: settled entries evict oldest-first.
        self._migrated: "OrderedDict[str, _Pending]" = OrderedDict()
        self._migrated_cap = 256
        self._stop = False
        self._abort = False  # drain budget blown: fail leftovers, exit
        self._draining = False
        self._failed = False  # restart budget exhausted
        self._restarting = False
        self._degraded = False  # last completed step blew the budget
        self._open = 0  # unsettled pendings (drain accounting)
        self.restarts = 0
        self._step_started: Optional[float] = None
        self.last_step_s: Optional[float] = None
        self._thread = threading.Thread(
            target=self._loop, name="serving-engine", daemon=True
        )
        self._thread.start()

    # -- observability -------------------------------------------------

    def status(self) -> str:
        """``healthy | degraded | restarting | draining | failed`` —
        what /health reports and /ready keys off. "degraded" covers
        both a completed iteration that blew ``step_time_budget_s`` and
        an iteration currently running past it (a hung device call
        cannot be interrupted, but it CAN be reported while stuck)."""
        now = time.perf_counter()
        with self._cond:
            if self._failed:
                return "failed"
            if self._draining or self._stop:
                return "draining"
            if self._restarting:
                return "restarting"
            started = self._step_started
            overrunning = (
                self._step_budget > 0 and started is not None
                and now - started > self._step_budget
            )
            if self._degraded or overrunning:
                return "degraded"
            return "healthy"

    def accepting(self) -> bool:
        """The /ready contract: route traffic here? False while
        draining/failed (submits are refused) AND while restarting
        (submits are accepted — they queue behind the rebuild — but a
        load balancer with other replicas should prefer them)."""
        return self.status() in ("healthy", "degraded")

    def stats_snapshot(self) -> dict:
        """Point-in-time engine stats for /health. Taken under the
        runner lock AND through StatsMap.snapshot (per-counter locks),
        so a snapshot never reads a counter mid-update from the engine
        thread — the old ``dict(engine.stats)`` shallow copy could.
        Plain-dict test doubles degrade to a locked dict() copy."""
        with self._cond:
            stats = self.engine.stats
            snap = getattr(stats, "snapshot", None)
            return snap() if snap is not None else dict(stats)

    # -- submission ----------------------------------------------------

    def submit(self, prompt: Sequence[int],
               params: Optional[SamplingParams] = None,
               deadline_s: Optional[float] = None,
               trace=None, journal_id=None, **kw) -> _Pending:
        """Thread-safe enqueue; returns the request's :class:`_Pending`
        handle. Raises :class:`QueueFullError` IMMEDIATELY when the
        admission bound (ServingConfig.max_queue_len) is hit — counting
        both the engine's wait queue and requests still in this runner's
        hand-off deque — so overload degrades into fast rejections the
        caller can act on; raises :class:`ShuttingDownError` while
        draining/closed. ``deadline_s`` is a server-side budget in
        seconds from now; the engine stops working on the request once
        it expires (the caller gets :class:`DeadlineExceededError`).
        ``trace`` is the request's cross-process TraceContext
        (obs/trace.py), forwarded to the engine for span stamping.
        Submissions during a supervised engine restart are accepted —
        they queue and run once the rebuilt engine is up."""
        params = params or SamplingParams(**kw)
        deadline = (
            time.perf_counter() + deadline_s
            if deadline_s is not None else None
        )
        pending = _Pending(list(prompt), params, deadline, trace,
                           journal_id=journal_id)
        with self._cond:
            if self._failed:
                err = EngineCrashError(
                    f"engine restart budget exhausted "
                    f"({self.max_restarts}); runner is dead"
                )
                # the class default says retriable, but THIS runner can
                # never recover — retry clients must fail over, not wait
                err.retriable = False
                raise err
            if self._draining or self._stop:
                raise ShuttingDownError(
                    "server is draining; retry against another replica"
                )
            maxq = self.engine.serving.max_queue_len
            # cancelled-but-undrained pendings no longer occupy the wait
            # queue they are counted against — a burst of client
            # timeouts must not cause spurious 503s for the next caller
            waiting = sum(1 for p in self._incoming if not p.cancelled)
            if maxq and waiting + self.engine.queue_len() >= maxq:
                _inc_stat(self.engine.stats, "rejected")
                raise QueueFullError(
                    f"admission queue full ({maxq} waiting); retry later"
                )
            self._incoming.append(pending)
            self._open += 1
            self._cond.notify()
        return pending

    def cancel(self, pending: _Pending) -> None:
        """Abandon a request: if still in the hand-off deque it is
        dropped before ever reaching the engine; if already admitted,
        the engine reclaims its queue entry / KV slot on the next loop
        pass (serving/engine.py:cancel). Safe to call concurrently with
        completion — a request that finished first just ignores it."""
        with self._cond:
            pending.cancelled = True
            self._cancels.append(pending)
            self._cond.notify()

    def generate(self, prompt: Sequence[int],
                 params: Optional[SamplingParams] = None,
                 timeout: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 trace=None, journal_id=None, **kw) -> RequestOutput:
        pending = self.submit(prompt, params, deadline_s=deadline_s,
                              trace=trace, journal_id=journal_id, **kw)
        if not pending.done.wait(timeout):
            # reclaim the engine-side resources before giving up — the
            # old behavior decoded to completion for nobody, pinning a
            # KV slot other callers were queued for
            self.cancel(pending)
            raise TimeoutError("generation timed out")
        if pending.error is not None:
            raise pending.error
        return pending.result

    # -- live migration (serving/migrate.py) ---------------------------

    def run_on_engine(self, fn, timeout: float = 30.0):
        """Run ``fn()`` ON the engine thread between steps and return
        its result (or re-raise its exception) to the calling thread.
        The engine is single-threaded by contract — this is the only
        sanctioned way for an HTTP handler to touch engine state.
        Accepted while draining (drain-time migration IS the point),
        refused once the runner is stopped or failed."""
        done = threading.Event()
        box: dict = {}

        def thunk():
            try:
                box["result"] = fn()
            except BaseException as e:
                box["error"] = e
            finally:
                done.set()

        with self._cond:
            if self._failed or self._stop:
                raise ShuttingDownError(
                    "runner is stopped; no engine thread to run on"
                )
            self._commands.append(thunk)
            self._cond.notify()
        if not done.wait(timeout):
            raise TimeoutError(
                f"engine command did not complete within {timeout}s"
            )
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def migrate_out(self, request_id: int, dest_url: str,
                    migrate_id: str, budget_s: float = 10.0) -> dict:
        """Migrate one in-flight request's live decode state to a peer
        replica: probe the destination's radix tree (dedup), export the
        slot's checksummed wire image, POST it to ``dest/migrate/import``
        under the transfer budget, then release the local slot and
        settle its waiter with :class:`MigratedError` — the blocked
        /generate handler answers 200 ``{"code": "migrated"}`` and the
        router awaits the peer.

        Only the device touches (export snapshot, release) run as
        engine-thread commands; the NETWORK legs (probe, transfer) run
        on the calling HTTP-handler thread. A slow or unreachable
        destination therefore costs the migrating request its budget —
        never the co-resident in-flight decodes, which keep stepping
        throughout. The slot also keeps decoding between snapshot and
        release; any tokens it emits past the snapshot are regenerated
        bit-exactly at the destination (the fold_in key chain is a pure
        function of ``t``), so a stale image is never a wrong image.
        Raises :class:`MigrateExportError` (typed ``code``) when any
        rung fails — the caller's fallback is replay."""
        budget = max(0.1, float(budget_s))
        deadline = time.monotonic() + budget

        def read_prompt():
            if self._waiters.get(request_id) is None:
                return None
            slot = self.engine._slot_for(request_id)
            return (
                [int(t) for t in slot.prompt]
                if slot is not None else []
            )

        prompt = self.run_on_engine(read_prompt)
        if prompt is None:
            # finished (or never admitted here): its /generate already
            # answered with the real result — nothing to move
            return {"outcome": "finished"}

        cached = 0
        if prompt:
            try:
                status, body, _ = http_post_json_with_retries(
                    dest_url + "/migrate/probe",
                    {"prompt_ids": prompt},
                    timeout=min(5.0, budget), max_retries=0,
                    deadline_s=max(0.1, deadline - time.monotonic()),
                )
                if status == 200:
                    cached = int(body.get("cached_pages", 0) or 0)
            except Exception:
                cached = 0  # probe is best-effort: dedup off

        def export():
            if self._waiters.get(request_id) is None:
                return None
            return self.engine.export_slot_state(
                request_id, dedup_pages=cached
            )

        blob = self.run_on_engine(export)
        if blob is None:
            return {"outcome": "finished"}

        status, body, _ = http_post_json_with_retries(
            dest_url + "/migrate/import",
            {"state": to_wire(blob), "migrate_id": migrate_id},
            timeout=max(0.1, deadline - time.monotonic()),
            max_retries=2,
            deadline_s=max(0.1, deadline - time.monotonic()),
        )
        if status != 200:
            code = body.get("code") if isinstance(body, dict) else None
            _inc_stat(self.engine.stats, "migrate_failed")
            raise MigrateExportError(
                f"destination import failed (status {status}, "
                f"code {code})", code="migrate_transfer",
            )

        def release():
            pending = self._waiters.get(request_id)
            if pending is None or pending.settled:
                # finished locally during the transfer: the real result
                # already answered the client; the imported copy decodes
                # the same tokens at dest and idles in its bounded
                # _migrated LRU until evicted
                return {"outcome": "finished"}
            self.engine.release_migrated(request_id)
            self._waiters.pop(request_id, None)
            self._settle(
                pending, error=MigratedError(dest_url, migrate_id)
            )
            return {
                "outcome": "migrated",
                "bytes": len(blob),
                "dedup_pages": cached,
                "dest": dest_url,
                "migrate_id": migrate_id,
            }

        return self.run_on_engine(release)

    def import_state(self, blob: bytes, migrate_id: str,
                     timeout: float = 30.0) -> int:
        """Land a migrated slot here: decode + CRC-verify the wire
        image, re-admit it through the zero-recompile swap-in path
        (serving/engine.py:import_state), and register a synthetic
        waiter under ``migrate_id`` for ``/migrate/await``. Runs on the
        engine thread. Raises :class:`MigratePayloadError` on a
        convicted transfer (never garbage KV), typed admission errors
        (QueueFullError, PagePoolExhaustedError) when full."""
        with self._cond:
            if self._draining or self._stop or self._failed:
                raise ShuttingDownError(
                    "replica is draining; migrate elsewhere"
                )

        def thunk():
            rid = self.engine.import_state(blob)
            pending = _Pending([], None)
            pending.rid = rid
            self._waiters[rid] = pending
            with self._cond:
                self._open += 1
                self._migrated[migrate_id] = pending
                while len(self._migrated) > self._migrated_cap:
                    oldest = next(iter(self._migrated))
                    if not self._migrated[oldest].settled:
                        break  # never drop a live import
                    self._migrated.popitem(last=False)
            return rid

        return self.run_on_engine(thunk, timeout=timeout)

    def migrated_pending(self, migrate_id: str) -> Optional[_Pending]:
        with self._cond:
            return self._migrated.get(migrate_id)

    def inflight_snapshot(self) -> list:
        """The last completed step's per-request progress (request_id,
        prompt_len, emitted tokens so far, journal_id when the router
        supplied one). Read lock-free by the router's probe loop into
        its ReplayJournal — a stale snapshot only means a few tokens
        get re-generated bit-exactly on replay."""
        with self._cond:
            return list(self._inflight)

    # -- shutdown ------------------------------------------------------

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: stop admission (new submits raise
        :class:`ShuttingDownError` -> HTTP 503 + Retry-After), wait for
        every accepted request to settle within the drain budget
        (``ServingConfig.drain_timeout_s`` unless overridden), then
        close the runner. Returns True when everything in flight
        completed; False when the budget expired and the stragglers
        were failed with :class:`ShuttingDownError`."""
        budget = (
            self.engine.serving.drain_timeout_s
            if timeout is None else timeout
        )
        end = time.monotonic() + budget
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            while (
                (self._open > 0 or self._incoming
                 or self.engine.has_work())
                and self._thread.is_alive()
            ):
                left = end - time.monotonic()
                if left <= 0:
                    break
                self._cond.wait(min(left, 0.1))
            drained = (
                self._open == 0 and not self._incoming
                and not self.engine.has_work()
            )
            if not drained:
                # budget blown: the loop fails leftovers on its next
                # pass and exits — nobody is left hanging
                self._abort = True
                self._cond.notify_all()
        self.close()
        return drained

    def close(self, timeout: float = 30.0) -> None:
        """Stop the loop (after it finishes in-engine work) and join
        the thread. Raises RuntimeError when the thread does not stop
        within ``timeout`` — a stuck device call means engine state is
        untrusted, and silently leaking the thread (the old behavior)
        hid exactly the wedged-server condition operators must see."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout)
        if self._thread.is_alive():
            with self._cond:
                # a wedged engine is a FAILED runner, not a routine
                # drain — /health must say so for as long as it answers
                self._failed = True
            raise RuntimeError(
                f"serving-engine thread failed to stop within {timeout}s "
                "(stuck in an engine step?); leaking the thread — engine "
                "state is untrusted, do not reuse this runner"
            )
        # engine-side host resources (the device-profile sampler's
        # parse worker) drain only after the loop thread is down — the
        # engine is single-threaded by contract. getattr: test doubles
        # keep their narrow surface.
        engine_close = getattr(self.engine, "close", None)
        if engine_close is not None:
            engine_close()

    # -- internals -----------------------------------------------------

    def _settle(self, pending: _Pending, result=None, error=None) -> bool:
        """Exactly-once delivery + drain accounting. Cancelled requests
        are settled too (their caller already unwound; the bookkeeping
        must not wait on them forever)."""
        with self._cond:
            if pending.settled:
                return False
            pending.settled = True
            pending.result = result
            pending.error = error
            self._open -= 1
            self._cond.notify_all()
        pending.done.set()
        return True

    def _deliver(self, outs, waiters: dict) -> None:
        """Settle finished engine outputs with their waiters (normal
        completion or a typed deadline error)."""
        for out in outs:
            pending = waiters.pop(out.request_id, None)
            if pending is None:
                continue
            if out.finish_reason == "deadline":
                self._settle(pending, error=DeadlineExceededError(
                    f"request {out.request_id} exceeded its "
                    f"server-side deadline after {len(out.tokens)} "
                    "generated tokens", output=out,
                ))
            elif out.finish_reason == "page_exhausted":
                err = PagePoolExhaustedError(
                    f"request {out.request_id} shed at admission: KV "
                    "page pool exhausted; retry later"
                )
                err.output = out
                if out.retry_after is not None:
                    # drain-rate-derived backoff hint (PagePool.
                    # estimated_drain_s): serving/retry.py uses it as
                    # the backoff floor and the HTTP 503 echoes it in
                    # Retry-After, so clients wait for actual pool
                    # drain time instead of a static guess
                    err.retry_after = out.retry_after
                self._settle(pending, error=err)
            elif out.finish_reason == "constraint_dead_end":
                # typed retriable failure with the partial output
                # attached — the HTTP layer maps it to 400
                # "constraint_dead_end" (serving/constrain.py)
                self._settle(pending, error=ConstraintDeadEndError(
                    f"request {out.request_id} hit a constraint dead "
                    f"end after {len(out.tokens)} generated tokens",
                    output=out,
                ))
            else:
                self._settle(pending, result=out)

    def _handle_engine_crash(self, exc: BaseException, waiters: dict) -> bool:
        """Supervised recovery from a failed engine step. Returns True
        when the loop should continue on the rebuilt engine, False when
        it must exit (restart budget exhausted, or the engine cannot be
        rebuilt). Mirrors tools/train_supervisor.py: typed failure,
        bounded exponential backoff, restart budget."""
        if isinstance(exc, EngineCrashError):
            crash = exc
        else:
            crash = EngineCrashError(f"engine step failed: {exc!r}")
            crash.__cause__ = exc
        # requests that finished EARLIER in the crashed step were
        # already retired from the scheduler — deliver them now, or
        # they are reachable from nowhere (not lost, not queued) and
        # their callers hang, the exact failure this layer removes
        take = getattr(self.engine, "take_finished", None)
        if take is not None:
            self._deliver(take(), waiters)
        with self._cond:
            # /health reads restarts from HTTP handler threads; publish
            # the bump under the runner lock like every other state bit
            self.restarts += 1
        rebuild = getattr(self.engine, "reset_after_crash", None)
        fatal = rebuild is None or self.restarts > self.max_restarts
        lost: List[int] = []
        if not fatal:
            with self._cond:
                self._restarting = True
            try:
                # fresh slot pool from params; wait-queue entries
                # survive verbatim (same rids -> same waiters)
                lost = rebuild()
            except Exception as e:  # cannot rebuild: give up
                print(f"[serving] engine rebuild failed: {e!r}",
                      file=sys.stderr)
                fatal = True
        if fatal:
            crash.retriable = False  # no restart is coming
            with self._cond:
                self._failed = True
                self._stop = True
                incoming = list(self._incoming)
                self._incoming.clear()
                self._restarting = False
            for p in list(waiters.values()):
                self._settle(p, error=crash)
            waiters.clear()
            for p in incoming:
                self._settle(p, error=crash)
            print(
                f"[serving] engine crashed ({exc!r}); restart budget "
                f"exhausted ({self.max_restarts}) — runner failed",
                file=sys.stderr,
            )
            return False
        # in-flight requests lost device state: fail them typed; queued
        # ones ride through the restart untouched
        for rid in lost:
            p = waiters.pop(rid, None)
            if p is not None:
                self._settle(p, error=crash)
        delay = min(
            self._backoff_base * (2 ** (self.restarts - 1)),
            self._backoff_max,
        )
        print(
            f"[serving] engine crashed ({exc!r}); slot pool rebuilt, "
            f"restart {self.restarts}/{self.max_restarts}, resuming in "
            f"{delay:.2f}s ({len(lost)} in-flight failed, "
            f"{self.engine.queue_len()} queued preserved)",
            file=sys.stderr,
        )
        end = time.monotonic() + delay
        while time.monotonic() < end:
            with self._cond:
                if self._stop or self._abort:
                    break
            time.sleep(min(0.05, max(0.0, end - time.monotonic())))
        with self._cond:
            self._restarting = False
        return True

    def _loop(self) -> None:
        waiters = self._waiters  # request_id -> _Pending (this thread's)
        while True:
            with self._cond:
                while (
                    not self._incoming
                    and not self._cancels
                    and not self._commands
                    and not self.engine.has_work()
                    and not self._abort
                ):
                    if self._stop:
                        return
                    self._cond.wait()
                incoming = list(self._incoming)
                self._incoming.clear()
                cancels = list(self._cancels)
                self._cancels.clear()
                commands = list(self._commands)
                self._commands.clear()
                stopping = self._stop
                aborting = self._abort
            if aborting:
                err = ShuttingDownError(
                    "server shut down before completing this request "
                    "(drain budget expired)"
                )
                for p in list(waiters.values()):
                    self._settle(p, error=err)
                for p in incoming:
                    self._settle(p, error=err)
                return
            for pending in cancels:
                if pending.rid is not None:
                    if self.engine.cancel(pending.rid):
                        w = waiters.pop(pending.rid, None)
                        if w is not None:
                            self._settle(
                                w, error=TimeoutError("cancelled")
                            )
                # rid None: either still in `incoming` (settled below) or
                # it finished before the cancel landed — nothing to undo
            for pending in incoming:
                if pending.cancelled:
                    self._settle(
                        pending,
                        error=TimeoutError("cancelled before admission"),
                    )
                    continue
                try:
                    # optional kwargs passed only when set, so plain
                    # test-double engines keep their narrow signatures
                    opt = {}
                    if pending.deadline is not None:
                        opt["deadline"] = pending.deadline
                    if pending.trace is not None:
                        opt["trace"] = pending.trace
                    pending.rid = self.engine.submit(
                        pending.prompt, params=pending.params, **opt
                    )
                    waiters[pending.rid] = pending
                except Exception as e:  # invalid request: fail the caller
                    self._settle(pending, error=e)
            for thunk in commands:
                # migration export/import thunks (run_on_engine): each
                # captures its own exception and signals its caller
                thunk()
            try:
                t0 = time.perf_counter()
                # the watchdog state is read by status() from HTTP
                # handler threads — publish every transition under the
                # runner lock (the engine step itself runs unlocked)
                with self._cond:
                    self._step_started = t0
                outs = self.engine.step()
                dt = time.perf_counter() - t0
                announce_degraded = False
                with self._cond:
                    self._step_started = None
                    self.last_step_s = dt
                    if self._step_budget > 0:
                        if dt > self._step_budget and not self._degraded:
                            self._degraded = True
                            announce_degraded = True
                        elif dt <= self._step_budget and self._degraded:
                            self._degraded = False
                if announce_degraded:
                    print(
                        f"[serving] watchdog: engine iteration took "
                        f"{dt:.3f}s (budget {self._step_budget}s) — "
                        "marking degraded", file=sys.stderr,
                    )
            except Exception as e:
                with self._cond:
                    self._step_started = None
                if not self._handle_engine_crash(e, waiters):
                    return
                continue
            self._deliver(outs, waiters)
            progress = getattr(self.engine, "progress_snapshot", None)
            if progress is not None:
                entries = progress()
                for ent in entries:
                    p = waiters.get(ent.get("request_id"))
                    if p is not None and p.journal_id is not None:
                        ent["journal_id"] = p.journal_id
                with self._cond:
                    self._inflight = entries
            if stopping and not self.engine.has_work():
                return


class ServingClient:
    """In-process client: one engine, blocking calls from any thread."""

    def __init__(self, engine: ServingEngine):
        self.runner = EngineRunner(engine)

    def generate(self, prompt: Sequence[int],
                 params: Optional[SamplingParams] = None,
                 timeout: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 trace=None, journal_id=None, **kw) -> RequestOutput:
        return self.runner.generate(
            prompt, params, timeout=timeout, deadline_s=deadline_s,
            trace=trace, journal_id=journal_id, **kw
        )

    def generate_batch(self, prompts: Sequence[Sequence[int]],
                       params: Optional[Sequence[SamplingParams]] = None,
                       timeout: Optional[float] = None,
                       **kw) -> List[RequestOutput]:
        """Submit all prompts, then wait — batched by the engine. A
        timeout cancels every still-unfinished request in the batch
        before raising (no orphaned decodes)."""
        shared = SamplingParams(**kw) if params is None else None
        handles = []
        try:
            for i, p in enumerate(prompts):
                handles.append(
                    self.runner.submit(p, shared if shared else params[i])
                )
        except Exception:
            # a mid-batch rejection (QueueFullError, closed runner) must
            # not orphan the prompts already accepted
            for h in handles:
                if not h.done.is_set():
                    self.runner.cancel(h)
            raise
        outs = []
        for pending in handles:
            ok = pending.done.wait(timeout)
            if not ok or pending.error is not None:
                # timeout OR one request failing: reclaim every still-
                # running sibling before raising — nothing may keep
                # decoding for a caller that is about to unwind
                for h in handles:
                    if not h.done.is_set():
                        self.runner.cancel(h)
                if not ok:
                    raise TimeoutError("generation timed out")
                raise pending.error
            outs.append(pending.result)
        return outs

    @property
    def stats(self) -> dict:
        return self.runner.stats_snapshot()

    @property
    def registry(self):
        """The engine's metrics registry (obs/registry.py) — what the
        HTTP server renders at GET /metrics; None on engines built
        without one (test doubles)."""
        return getattr(self.runner.engine, "registry", None)

    def status(self) -> str:
        return self.runner.status()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown; see :meth:`EngineRunner.drain`."""
        return self.runner.drain(timeout)

    def close(self) -> None:
        self.runner.close()


def _make_handler(client: ServingClient, tokenizer=None, events=None,
                  slo=None):
    events = events or NOOP_EVENTS

    class Handler(BaseHTTPRequestHandler):
        def _reply(self, code: int, payload: dict,
                   headers: Optional[dict] = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _retry_after(self) -> dict:
            # how long a well-behaved client should back off before
            # retrying this replica; draining lasts up to the drain
            # budget, everything else clears within ~a restart backoff
            serving = client.runner.engine.serving
            if client.runner.status() == "draining":
                secs = max(1, int(serving.drain_timeout_s))
            else:
                secs = max(1, int(serving.restart_backoff_s))
            return {"Retry-After": str(secs)}

        def do_GET(self):
            if self.path == "/metrics":
                registry = client.registry
                if registry is None:
                    self._reply(404, {"error": "no metrics registry"})
                    return
                if slo is not None:
                    # refresh the slo_* burn-rate gauges so every
                    # scrape carries a current judgment (obs/slo.py)
                    slo.evaluate()
                body = registry.render().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", METRICS_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path == "/health":
                status = client.status()
                payload = {
                    "ok": status in ("healthy", "degraded"),
                    "status": status,
                    "restarts": client.runner.restarts,
                    "last_step_s": client.runner.last_step_s,
                    "stats": client.stats,
                }
                # compile-cache sizes, so fleet chaos tests can pin
                # "zero added recompiles" on REMOTE replicas too
                compile_stats = getattr(
                    client.runner.engine, "compile_stats", None
                )
                if compile_stats is not None:
                    payload["compiles"] = compile_stats()
                # paged-KV pool snapshot (serving/pages.py): page
                # counts + prefix-cache hit/miss/eviction counters, so
                # operators and fleet chaos tests see capacity and
                # cache behavior without scraping /metrics
                page_stats = getattr(
                    client.runner.engine, "page_stats", None
                )
                if page_stats is not None:
                    pages = page_stats()
                    if pages is not None:
                        payload["kv_pages"] = pages
                # speculative-decoding snapshot (serving/spec.py):
                # mode, draft rung, proposed/accepted counters and
                # acceptance rate — the per-replica view the fleet
                # aggregation sums from /metrics
                spec_stats = getattr(
                    client.runner.engine, "spec_stats", None
                )
                if spec_stats is not None:
                    spec = spec_stats()
                    if spec is not None:
                        payload["spec"] = spec
                # structured-decoding snapshot (serving/constrain.py):
                # in-flight constrained requests + compile-cache
                # entries/bytes/hit/miss/eviction counters
                constrain_stats = getattr(
                    client.runner.engine, "constrain_stats", None
                )
                if constrain_stats is not None:
                    payload["constraints"] = constrain_stats()
                # host-tier snapshot (serving/host_tier.py): byte
                # budget/usage, cached/stashed entries, and the
                # demote/promote/preempt/resume/fallback counters —
                # the "Serving under memory pressure" runbook's
                # first-stop view
                tier_stats = getattr(
                    client.runner.engine, "tier_stats", None
                )
                if tier_stats is not None:
                    tier = tier_stats()
                    if tier is not None:
                        payload["host_tier"] = tier
                # per-priority-class queue depths: a saturating batch
                # class is visible as ITS queue growing, not as an
                # opaque aggregate number
                queue_depths = getattr(
                    client.runner.engine, "queue_depths", None
                )
                if queue_depths is not None:
                    payload["queue_by_class"] = queue_depths()
                self._reply(200, payload)
            elif self.path == "/ready":
                if client.runner.accepting():
                    self._reply(200, {"ready": True,
                                      "status": client.status()})
                else:
                    self._reply(
                        503, {"ready": False, "status": client.status()},
                        headers=self._retry_after(),
                    )
            elif self.path == "/inflight":
                # per-request progress for the router: replay-journal
                # harvest + drain-time migration enumeration
                self._reply(
                    200, {"inflight": client.runner.inflight_snapshot()}
                )
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        # -- live migration endpoints (serving/migrate.py) ------------

        def _read_json(self) -> dict:
            n = int(self.headers.get("Content-Length", "0"))
            return json.loads(self.rfile.read(n) or b"{}")

        def _migrate_probe(self) -> None:
            """How many leading prompt pages this replica's radix tree
            already holds — the source ships holes for them (dedup)."""
            try:
                req = self._read_json()
                prompt = [int(t) for t in req.get("prompt_ids") or []]
                pool = getattr(client.runner.engine, "_pages", None)
                cached = (
                    pool.probe_prefix(prompt)
                    if pool is not None and prompt else 0
                )
                self._reply(200, {"cached_pages": int(cached)})
            except Exception as e:
                self._reply(400, {"error": str(e), "code": "bad_request"})

        def _migrate_import(self) -> None:
            """Land a migrated slot: decode + CRC-verify, re-admit via
            the zero-recompile swap-in path. A convicted (corrupt/torn)
            payload answers a typed 409 — garbage KV never lands."""
            try:
                req = self._read_json()
                migrate_id = str(req.get("migrate_id") or "")
                if not migrate_id or "state" not in req:
                    raise ValueError("migrate_id and state required")
                blob = from_wire(str(req["state"]))
                rid = client.runner.import_state(blob, migrate_id)
            except MigratePayloadError as e:
                self._reply(409, {"error": str(e),
                                  "code": "migrate_corrupt"})
            except MigrateExportError as e:
                self._reply(409, {"error": str(e), "code": e.code})
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                self._reply(400, {"error": str(e), "code": "bad_request"})
            except QueueFullError as e:
                self._reply(503, {"error": str(e), "code": "queue_full"},
                            headers=self._retry_after())
            except PagePoolExhaustedError as e:
                self._reply(503, {"error": str(e),
                                  "code": "page_pool_exhausted"},
                            headers=self._retry_after())
            except ShuttingDownError as e:
                self._reply(503, {"error": str(e),
                                  "code": "shutting_down"},
                            headers=self._retry_after())
            except TimeoutError as e:
                self._reply(503, {"error": str(e),
                                  "code": "migrate_timeout"})
            except Exception as e:
                self._reply(500, {"error": str(e) or repr(e),
                                  "code": "internal"})
            else:
                events.emit("migrate_imported", migrate_id=migrate_id,
                            request_id=rid)
                self._reply(200, {"request_id": rid,
                                  "migrate_id": migrate_id})

        def _migrate_export(self) -> None:
            """Drain-side trigger: move one in-flight request to
            ``dest``. Any typed failure (contiguous layout, transfer
            death, dest full) answers non-200 so the router falls back
            to replay — the request itself is NEVER harmed (the slot
            keeps decoding unless the hand-off fully landed)."""
            try:
                req = self._read_json()
                result = client.runner.migrate_out(
                    int(req["request_id"]),
                    str(req["dest"]).rstrip("/"),
                    str(req.get("migrate_id") or ""),
                    budget_s=float(req.get("budget_s", 10.0)),
                )
            except MigrateExportError as e:
                self._reply(409, {"error": str(e), "code": e.code})
            except (ValueError, TypeError, KeyError,
                    json.JSONDecodeError) as e:
                self._reply(400, {"error": str(e), "code": "bad_request"})
            except ShuttingDownError as e:
                self._reply(503, {"error": str(e),
                                  "code": "shutting_down"})
            except TimeoutError as e:
                self._reply(503, {"error": str(e),
                                  "code": "migrate_timeout"})
            except Exception as e:
                self._reply(500, {"error": str(e) or repr(e),
                                  "code": "internal"})
            else:
                events.emit("migrate_exported",
                            outcome=result.get("outcome"),
                            dest=result.get("dest"))
                self._reply(200, result)

        def _run_generate(self, req: dict, ctx) -> RequestOutput:
            """Parse a /generate body into SamplingParams and run it;
            raises the typed errors do_POST's ladder maps to HTTP."""
            prompt_ids = req.get("prompt_ids")
            if prompt_ids is None and "prompt" in req:
                if tokenizer is None:
                    raise ValueError(
                        "text prompts need the server started with a "
                        "tokenizer dir; send prompt_ids instead"
                    )
                prompt_ids = tokenizer.encode(req["prompt"]).ids
            if not prompt_ids:
                raise ValueError("prompt_ids (or prompt) required")
            top_k = req.get("top_k")
            eos = req.get("eos_token_id")
            choices = req.get("choices")
            stop = req.get("stop")
            # json_schema arrives as a JSON VALUE (object) or a
            # pre-encoded string; SamplingParams wants the string
            schema = req.get("json_schema")
            if schema is not None and not isinstance(schema, str):
                schema = json.dumps(schema)
            params = SamplingParams(
                max_new_tokens=int(req.get("max_new_tokens", 16)),
                temperature=float(req.get("temperature", 1.0)),
                top_k=None if top_k is None else int(top_k),
                seed=int(req.get("seed", 0)),
                eos_token_id=None if eos is None else int(eos),
                json_schema=schema,
                regex=req.get("regex"),
                choices=choices,
                repetition_penalty=float(
                    req.get("repetition_penalty", 1.0)
                ),
                presence_penalty=float(
                    req.get("presence_penalty", 0.0)
                ),
                frequency_penalty=float(
                    req.get("frequency_penalty", 0.0)
                ),
                stop=(
                    None if stop is None
                    else tuple(
                        tuple(int(t) for t in seq) for seq in stop
                    )
                ),
                logprobs=int(req.get("logprobs", 0)),
                priority=str(req.get("priority", "normal")),
                # resume-by-replay (serving/migrate.py): the router
                # resubmits prompt+emitted with the key-chain position
                key_offset=int(req.get("key_offset", 0)),
            )
            deadline_s = req.get("deadline_s")
            # "received", not "admitted": a QueueFullError /
            # ShuttingDownError raised inside generate() means the
            # scheduler never accepted this request — true
            # admission is the engine's trace-stamped `admit`
            # instant; this event marks arrival at the handler
            events.emit("request_received", trace_id=ctx.trace_id,
                        prompt_len=len(prompt_ids))
            jid = req.get("journal_id")
            return client.generate(
                [int(t) for t in prompt_ids], params,
                timeout=float(req.get("timeout", 600.0)),
                deadline_s=(
                    None if deadline_s is None else float(deadline_s)
                ),
                trace=ctx,
                journal_id=None if jid is None else str(jid),
            )

        def do_POST(self):
            if self.path == "/migrate/probe":
                return self._migrate_probe()
            if self.path == "/migrate/import":
                return self._migrate_import()
            if self.path == "/migrate/export":
                return self._migrate_export()
            # /migrate/await shares /generate's error ladder and reply
            # shape — it IS a /generate whose work arrived by migration
            if self.path not in ("/generate", "/migrate/await"):
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            ctx = None  # TraceContext once the body parses

            def _fail(code: int, payload: dict, headers=None,
                      event: str = "request_failed") -> None:
                # every error reply carries the request's trace id (when
                # the body parsed far enough to have one) and lands one
                # structured event, so a failed request is findable in
                # both the stitched timeline and the event log
                if ctx is not None:
                    payload.setdefault("trace_id", ctx.trace_id)
                events.emit(event, status=code,
                            code=payload.get("code"),
                            trace_id=payload.get("trace_id"))
                self._reply(code, payload, headers=headers)

            try:
                n = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(n) or b"{}")
                # the traceparent JSON field is the cross-process trace
                # contract (obs/trace.py): the router mints and injects
                # one; a directly-hit replica mints its own, so replies
                # ALWAYS carry a trace_id a stitched timeline can find
                ctx = trace_from_payload(req)
                if self.path == "/migrate/await":
                    # pick up a migrated continuation: block on the
                    # imported request's waiter and reply in the exact
                    # /generate shape (COMPLETE token list — the slot
                    # restored the source's emitted tokens, so no
                    # router-side stitching is needed)
                    migrate_id = str(req.get("migrate_id") or "")
                    pending = client.runner.migrated_pending(migrate_id)
                    if pending is None:
                        _fail(404, {
                            "error": f"unknown migrate_id {migrate_id!r}",
                            "code": "unknown_migrate_id",
                        })
                        return
                    if not pending.done.wait(
                        float(req.get("timeout", 600.0))
                    ):
                        client.runner.cancel(pending)
                        raise TimeoutError("generation timed out")
                    if pending.error is not None:
                        raise pending.error
                    out = pending.result
                else:
                    out = self._run_generate(req, ctx)
            except ConstraintCompileError as e:
                # must precede the ValueError branch (it IS one): a
                # malformed/unsupported constraint spec fails typed at
                # submit with the engine untouched — a distinct code so
                # clients can tell "fix your schema" from "bad request"
                _fail(400, {"error": str(e),
                            "code": "constraint_compile_failed"})
                return
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                _fail(400, {"error": str(e), "code": "bad_request"})
                return
            except ConstraintDeadEndError as e:
                # the constraint FSM hit an all-zero mask mid-decode:
                # typed 400 with the partial output — retriable per the
                # error's flag, but a retry of the SAME spec dead-ends
                # again unless the fault was injected chaos
                _fail(400, {
                    "error": str(e),
                    "code": "constraint_dead_end",
                    "partial_tokens": (
                        e.output.tokens if e.output is not None else []
                    ),
                })
                return
            except QueueFullError as e:
                # overload: reject fast with the retryable status so
                # load balancers/clients back off instead of piling on.
                # Every error reply carries a machine-readable "code" —
                # serving/retry.py gates retries on it and the bench
                # classifies by it, so rewording the human text cannot
                # silently change client behavior.
                _fail(
                    503,
                    {"error": f"server overloaded: {e}",
                     "code": "queue_full"},
                    headers=self._retry_after(),
                )
                return
            except PagePoolExhaustedError as e:
                # the paged-KV shed path: same retryable 503 contract
                # as queue_full (the pool drains as requests retire and
                # cached prefixes evict); a never-fits request carries
                # retriable=False — no Retry-After, clients must not
                # burn their budget re-sending it here
                if getattr(e, "retriable", True):
                    # prefer the engine's drain-rate-derived estimate
                    # (seconds until enough pages free at the observed
                    # eviction/release throughput) over the static
                    # restart-backoff default
                    ra = getattr(e, "retry_after", None)
                    _fail(503, {"error": str(e),
                                "code": "page_pool_exhausted"},
                          headers=(
                              {"Retry-After":
                               str(max(1, int(round(ra))))}
                              if ra is not None
                              else self._retry_after()
                          ))
                else:
                    _fail(503, {"error": str(e),
                                "code": "page_pool_unfit"})
                return
            except ShuttingDownError as e:
                _fail(503, {"error": str(e),
                            "code": "shutting_down"},
                      headers=self._retry_after())
                return
            except EngineCrashError as e:
                if getattr(e, "retriable", True):
                    # the supervised restart is already underway — a
                    # retry after the backoff lands on the rebuilt engine
                    _fail(
                        503, {"error": f"engine crashed: {e}",
                              "code": "engine_crash"},
                        headers=self._retry_after(),
                    )
                else:
                    # restart budget exhausted: this replica will NEVER
                    # recover — no Retry-After, non-retriable code, so
                    # clients fail over instead of burning their budget
                    _fail(503, {"error": str(e),
                                "code": "engine_failed"})
                return
            except DeadlineExceededError as e:
                _fail(504, {
                    "error": str(e),
                    "code": "deadline",
                    "partial_tokens": (
                        e.output.tokens if e.output is not None else []
                    ),
                })
                return
            except TimeoutError:
                # the request burned its FULL generation timeout — a
                # retry would re-add that same load to a server at its
                # slowest, so: no Retry-After, non-retriable code
                _fail(503, {"error": "generation timed out",
                            "code": "timeout"})
                return
            except MigratedError as e:
                # not a failure: the live state moved to a peer mid-
                # flight — 200 with the forwarding pointer, and the
                # router picks the continuation up at dest's
                # /migrate/await
                payload = {"code": "migrated", "dest": e.dest,
                           "migrate_id": e.migrate_id}
                if ctx is not None:
                    payload["trace_id"] = ctx.trace_id
                events.emit("request_migrated", dest=e.dest,
                            trace_id=payload.get("trace_id"))
                self._reply(200, payload)
                return
            except Exception as e:  # unexpected failure — still typed:
                # the router (serving/router.py) and retry client key
                # retriability off the machine-readable "code"; an
                # untyped stack-trace 500 would strand them guessing
                _fail(500, {"error": str(e) or repr(e),
                            "code": "internal"})
                return
            payload = {
                "request_id": out.request_id,
                "prompt_ids": out.prompt,
                "tokens": out.tokens,
                "finish_reason": out.finish_reason,
                "ttft_ms": round(out.ttft * 1e3, 3),
                "trace_id": out.trace_id or ctx.trace_id,
            }
            if out.token_logprobs is not None:
                payload["token_logprobs"] = out.token_logprobs
                payload["top_logprobs"] = [
                    [[tid, lp] for tid, lp in row]
                    for row in out.top_logprobs
                ]
            if out.quality is not None:
                payload["quality"] = out.quality
            if tokenizer is not None:
                payload["text"] = tokenizer.decode(out.tokens)
            events.emit("request_finished",
                        trace_id=payload["trace_id"],
                        reason=out.finish_reason,
                        tokens=len(out.tokens),
                        ttft_ms=payload["ttft_ms"])
            self._reply(200, payload)

        def log_message(self, *a):  # quiet by default
            pass

    return Handler


def serve(client: ServingClient, host: str = "127.0.0.1", port: int = 8000,
          tokenizer=None, events=None, slo=None) -> ThreadingHTTPServer:
    """Build the HTTP server (not yet serving; call serve_forever()).
    ``events`` is an obs/events.py EventLog (None = off); ``slo`` an
    obs/slo.py SLOMonitor evaluated on every /metrics scrape."""
    return ThreadingHTTPServer(
        (host, port), _make_handler(client, tokenizer, events, slo)
    )


def main() -> None:
    """CLI: serve a checkpoint (or a random-init demo model) over HTTP."""
    import argparse

    import jax

    from differential_transformer_replication_tpu.config import (
        ModelConfig,
        ServingConfig,
    )

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--checkpoint", default=None,
                   help="training checkpoint dir (meta.json + "
                        "state.msgpack); omit for a random-init demo model")
    p.add_argument("--tokenizer", default=None,
                   help="tokenizer dir enabling text prompts "
                        "(vocab.json + merges.txt)")
    p.add_argument("--model", default="control",
                   help="demo model family when no checkpoint is given")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--num-slots", type=int, default=8)
    p.add_argument("--prefill-chunk", type=int, default=128)
    p.add_argument("--prefill-budget", type=int, default=256)
    p.add_argument("--max-seq-len", type=int, default=0)
    p.add_argument("--decode-attention-impl", default="",
                   choices=("", "xla", "pallas"),
                   help="decode attention backend: the fused Pallas "
                        "single-query kernel (ops/decode_attention.py) "
                        "or plain XLA; '' keeps the model config")
    p.add_argument("--kv-cache-dtype", default="",
                   choices=("", "auto", "bf16", "int8"),
                   help="KV-cache storage dtype; int8 stores per-head-"
                        "scale quantized K/V — about half the bf16 HBM "
                        "bytes per slot, so ~2x slot capacity at equal "
                        "memory; '' keeps the model config")
    p.add_argument("--kv-page-size", type=int, default=0,
                   help="paged KV cache (serving/pages.py): tokens per "
                        "page (must divide block_size); admission then "
                        "keys on free pages, not slots, so short "
                        "requests stop paying worst-case-context HBM. "
                        "0 = contiguous per-slot rings")
    p.add_argument("--kv-pool-pages", type=int, default=0,
                   help="total physical pages in the paged pool; 0 = "
                        "auto (num_slots * block_size / page_size). "
                        "Sizing below auto converts short-context "
                        "traffic into more concurrent slots at equal "
                        "HBM")
    p.add_argument("--no-prefix-cache", action="store_true",
                   help="disable the radix-tree shared-prefix cache "
                        "(on by default when --kv-page-size > 0): "
                        "retired prompts donate KV pages so requests "
                        "sharing a system prompt skip its prefill")
    p.add_argument("--prefix-cache-pages", type=int, default=0,
                   help="extra pool pages reserved as cached-prefix "
                        "headroom on top of the auto sizing")
    p.add_argument("--host-tier-bytes", type=int, default=0,
                   help="host-RAM KV page tier (serving/host_tier.py), "
                        "in bytes (needs --kv-page-size): evicted "
                        "radix-cached prefixes DEMOTE here instead of "
                        "vanishing and promote back with a copy, never "
                        "a recompute; preempted requests stash their "
                        "live KV here and resume bit-exact. 0 = off")
    p.add_argument("--priority-aging", type=float, default=10.0,
                   help="anti-starvation aging (seconds): every this "
                        "many seconds waited improves a queued "
                        "request's effective priority by one class, so "
                        "batch traffic cannot starve under sustained "
                        "high-priority load (0 = strict classes)")
    p.add_argument("--priority-max-slots", default="",
                   help="per-class slot bounds as 'class:N,...' (e.g. "
                        "'batch:6') capping how many slots one class "
                        "may hold; '' = no bounds")
    p.add_argument("--spec-mode", default="",
                   choices=("", "ngram", "model"),
                   help="speculative decoding (serving/spec.py): "
                        "'ngram' = drafter-free prompt lookup over "
                        "each request's own tokens; 'model' = a small "
                        "drafter checkpoint (--spec-drafter-ckpt) "
                        "proposing greedily on its own KV pool. The "
                        "target verifies k drafted tokens per slot in "
                        "ONE fused multi-row step — greedy output "
                        "stays bit-identical to non-spec decoding")
    p.add_argument("--spec-draft-len", type=int, default=4,
                   help="draft tokens verified per slot per iteration "
                        "(the compiled k rung; per-request lengths "
                        "ride as runtime arrays)")
    p.add_argument("--spec-drafter-ckpt", default="",
                   help="drafter checkpoint dir for --spec-mode model "
                        "(loaded like --checkpoint: manifest "
                        "verification and --quantize-weights apply); "
                        "must share the target's tokenizer/vocab")
    p.add_argument("--spec-verify", default="exact",
                   choices=("exact", "batched"),
                   help="verify-step formulation: 'exact' (unrolled, "
                        "greedy bit-identical to non-spec at any "
                        "size) or 'batched' (each slot's KV streamed "
                        "once for all k+1 rows through the fused "
                        "multi-query kernel — the TPU-bandwidth "
                        "formulation)")
    p.add_argument("--quantize-weights", default=None,
                   choices=("int8",),
                   help="per-channel int8 quantize + dequant of every "
                        "matmul weight at checkpoint load "
                        "(tolerance-gated accuracy)")
    p.add_argument("--max-queue-len", type=int, default=0,
                   help="reject (HTTP 503) submissions past this many "
                        "waiting requests; 0 = unbounded")
    p.add_argument("--default-deadline", type=float, default=0.0,
                   help="server-side deadline (seconds) applied to "
                        "requests that do not send deadline_s; expired "
                        "requests are shed instead of decoded (0 = none)")
    p.add_argument("--drain-timeout", type=float, default=30.0,
                   help="graceful-drain budget on SIGTERM: stop "
                        "admission, finish in-flight within this many "
                        "seconds, then exit")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="supervised engine-restart budget; a crashed "
                        "engine step rebuilds the slot pool up to this "
                        "many times before the server fails hard")
    p.add_argument("--restart-backoff", type=float, default=0.5,
                   help="first-restart backoff seconds (doubles per "
                        "restart, like tools/train_supervisor.py)")
    p.add_argument("--restart-backoff-max", type=float, default=30.0,
                   help="restart backoff cap in seconds")
    p.add_argument("--step-time-budget", type=float, default=0.0,
                   help="watchdog: mark the engine degraded on /health "
                        "when one decode iteration exceeds this many "
                        "seconds (0 = off)")
    p.add_argument("--profile-every", type=int, default=0,
                   help="continuous on-device profiling "
                        "(obs/device_profile.py): every N engine "
                        "iterations capture ONE iteration's device "
                        "profile, parse it off-loop, and publish "
                        "device_* gauges on /metrics, device_profile "
                        "JSONL rows and a stitchable device-lane trace "
                        "under --profile-dir; 0 = off")
    p.add_argument("--profile-dir", default="device_profiles",
                   help="rotating spool for --profile-every captures")
    p.add_argument("--trace-path", default=None,
                   help="write a Chrome-trace-event JSON of engine "
                        "iterations (schedule/prefill/decode/sample/emit "
                        "spans + per-request trace-stamped lifecycle; "
                        "open in Perfetto or merge fleet-wide with "
                        "tools/trace_stitch.py) to this path")
    p.add_argument("--event-log", default=None,
                   help="append structured JSONL events (request "
                        "received/finished/failed with trace ids; "
                        "obs/events.py) to this path")
    p.add_argument("--event-log-max-bytes", type=int, default=0,
                   help="rotate --event-log when it reaches this many "
                        "bytes (atomic rename cascade, whole lines "
                        "only; 0 = never rotate)")
    p.add_argument("--event-log-keep", type=int, default=3,
                   help="rotated --event-log generations to keep "
                        "(events.jsonl.1 ... .N; 0 = truncate)")
    p.add_argument("--quality-telemetry", action="store_true",
                   help="compute per-token model-quality signals "
                        "(sampled-distribution entropy, top-1 logit "
                        "margin, repetition runs) inside the jitted "
                        "decode step (obs/quality.py): per-request "
                        "quality stats on responses, "
                        "serving_token_entropy / serving_logit_margin "
                        "histograms and serving_lambda_mean{layer=} / "
                        "serving_quality_drift gauges on /metrics")
    p.add_argument("--quality-fingerprint", default=None,
                   help="reference quality fingerprint JSON to compare "
                        "live traffic against (PSI drift score as "
                        "serving_quality_drift; recorded earlier with "
                        "--quality-record); implies --quality-telemetry")
    p.add_argument("--quality-record", default=None,
                   help="write this replica's quality fingerprint "
                        "(quantile sketches of the live entropy/margin "
                        "distributions) to this path at drain/shutdown; "
                        "implies --quality-telemetry")
    p.add_argument("--slo-ttft", type=float, default=1.0,
                   help="TTFT latency objective bound in seconds "
                        "(obs/slo.py; burn rates exposed as slo_* "
                        "gauges on /metrics)")
    p.add_argument("--slo-itl", type=float, default=0.25,
                   help="inter-token latency objective bound in seconds")
    p.add_argument("--slo-target", type=float, default=0.99,
                   help="latency objectives' target fraction of "
                        "requests under the bound")
    p.add_argument("--slo-availability-target", type=float,
                   default=0.999,
                   help="availability objective target (completed vs "
                        "rejected/deadline-expired)")
    p.add_argument("--no-verify-checkpoint", action="store_true",
                   help="skip integrity-manifest verification of "
                        "--checkpoint (needed for pre-manifest "
                        "checkpoints; or certify them once with "
                        "tools/ckpt_doctor.py --adopt-legacy)")
    args = p.parse_args()

    meta = None
    if args.checkpoint:
        from differential_transformer_replication_tpu.train.checkpoint import (
            load_params_for_inference,
        )

        params, model_cfg, meta = load_params_for_inference(
            args.checkpoint, verify=not args.no_verify_checkpoint,
            quantize=args.quantize_weights,
        )
    else:
        from differential_transformer_replication_tpu.models import init_model

        model_cfg = ModelConfig(
            model=args.model, vocab_size=512, n_embd=64, n_head=2,
            n_layer=2, block_size=128, compute_dtype="float32",
        )
        from differential_transformer_replication_tpu.train.checkpoint import (
            apply_weight_quantization,
        )

        params = apply_weight_quantization(
            init_model(jax.random.PRNGKey(0), model_cfg),
            args.quantize_weights,
        )
        print("[serve] no checkpoint given: random-init demo model")

    tokenizer = None
    # id -> decoded-string table for the constraint FSM compiler
    # (serving/constrain.py). Without a tokenizer the demo model maps
    # printable-ASCII ids to their characters so constrained requests
    # still work against the random-init model ("" = never allowed).
    vocab = [
        chr(i) if 32 <= i < 127 else ""
        for i in range(model_cfg.vocab_size)
    ]
    if args.tokenizer:
        from differential_transformer_replication_tpu.data.tokenizer import (
            check_tokenizer_matches,
            load_tokenizer,
            vocab_strings,
        )

        tokenizer = load_tokenizer(args.tokenizer)
        vocab = vocab_strings(tokenizer, model_cfg.vocab_size)
        if meta is not None:
            # refuse to serve text through a tokenizer that cannot belong
            # to the checkpoint (same guard as sample.py — a clobbered
            # shared tokenizer dir would silently emit garbage text)
            check_tokenizer_matches(
                tokenizer, model_cfg.vocab_size,
                meta.get("tokenizer_fingerprint"), context=args.checkpoint,
            )

    serving = ServingConfig(
        num_slots=args.num_slots, prefill_chunk=args.prefill_chunk,
        prefill_budget=args.prefill_budget, max_seq_len=args.max_seq_len,
        decode_attention_impl=args.decode_attention_impl,
        kv_cache_dtype=args.kv_cache_dtype,
        kv_page_size=args.kv_page_size,
        kv_pool_pages=args.kv_pool_pages,
        prefix_cache=not args.no_prefix_cache,
        prefix_cache_pages=args.prefix_cache_pages,
        host_tier_bytes=args.host_tier_bytes,
        priority_aging_s=args.priority_aging,
        priority_max_slots=args.priority_max_slots,
        max_queue_len=args.max_queue_len,
        default_deadline_s=args.default_deadline,
        drain_timeout_s=args.drain_timeout,
        max_restarts=args.max_restarts,
        restart_backoff_s=args.restart_backoff,
        restart_backoff_max_s=args.restart_backoff_max,
        step_time_budget_s=args.step_time_budget,
        profile_every=args.profile_every,
        profile_dir=args.profile_dir,
        spec_mode=args.spec_mode,
        spec_draft_len=args.spec_draft_len,
        spec_drafter_ckpt=args.spec_drafter_ckpt,
        spec_verify=args.spec_verify,
        # recording or comparing a fingerprint both need the in-step
        # telemetry tail, so either flag arms it
        quality_telemetry=(args.quality_telemetry
                           or bool(args.quality_fingerprint)
                           or bool(args.quality_record)),
        quality_fingerprint=args.quality_fingerprint or "",
    )
    spec_drafter = None
    if args.spec_mode == "model" and args.spec_drafter_ckpt:
        # load the drafter through the SAME verified/quantized path as
        # the target, so --no-verify-checkpoint / --quantize-weights
        # apply to it too
        from differential_transformer_replication_tpu.train.checkpoint import (
            load_params_for_inference as _load_drafter,
        )

        d_params, d_cfg, _ = _load_drafter(
            args.spec_drafter_ckpt,
            verify=not args.no_verify_checkpoint,
            quantize=args.quantize_weights,
        )
        spec_drafter = (d_params, d_cfg)
    tracer = None
    if args.trace_path:
        from differential_transformer_replication_tpu.obs.spans import (
            SpanTracer,
        )

        tracer = SpanTracer(args.trace_path, process_name="serving-engine")
    events = None
    if args.event_log:
        from differential_transformer_replication_tpu.obs.events import (
            EventLog,
        )

        events = EventLog(args.event_log, process="replica",
                          max_bytes=args.event_log_max_bytes,
                          keep=args.event_log_keep)
    engine = ServingEngine(params, model_cfg, serving, tracer=tracer,
                           spec_drafter=spec_drafter, vocab=vocab)
    client = ServingClient(engine)

    # process identity on /metrics: lets the router's aggregated
    # /fleet/metrics tell replicas apart and spot config drift
    import dataclasses as _dc
    import hashlib as _hashlib

    from differential_transformer_replication_tpu.obs.registry import (
        set_build_info,
    )
    from differential_transformer_replication_tpu.obs.slo import (
        SLOMonitor,
        default_serving_objectives,
    )

    cfg_hash = _hashlib.sha1(
        json.dumps(_dc.asdict(model_cfg), sort_keys=True,
                   default=str).encode()
    ).hexdigest()[:12]
    set_build_info(engine.registry, role="replica", config_hash=cfg_hash,
                   version=jax.__version__)
    slo_latency, slo_availability = default_serving_objectives(
        ttft_threshold_s=args.slo_ttft, itl_threshold_s=args.slo_itl,
        latency_target=args.slo_target,
        availability_target=args.slo_availability_target,
    )
    slo = SLOMonitor(engine.registry, latency=slo_latency,
                     availability=slo_availability)
    httpd = serve(client, args.host, args.port, tokenizer,
                  events=events, slo=slo)

    import signal

    drained = {"done": False}
    fingerprint_saved = {"done": False}

    def _save_quality_fingerprint():
        """Snapshot the live quality sketches to --quality-record;
        idempotent (drain path and main finally both call it)."""
        if not args.quality_record or fingerprint_saved["done"]:
            return
        fingerprint_saved["done"] = True
        try:
            from differential_transformer_replication_tpu.obs.quality import (
                save_fingerprint,
            )

            rec = engine.quality_fingerprint(
                meta={"model": model_cfg.model, "config_hash": cfg_hash}
            )
            save_fingerprint(args.quality_record, rec)
            print(f"[serve] quality fingerprint written to "
                  f"{args.quality_record}", file=sys.stderr)
        except Exception as e:  # forensics must not block shutdown
            print(f"[serve] quality fingerprint save failed: {e!r}",
                  file=sys.stderr)

    def _graceful(signum, frame):
        del frame
        print(f"[serve] signal {signum}: draining (budget "
              f"{serving.drain_timeout_s}s) — admission stopped",
              file=sys.stderr)

        def _drain_then_stop():
            try:
                ok = client.drain()
                print(f"[serve] drain {'complete' if ok else 'TIMED OUT'}; "
                      "shutting down", file=sys.stderr)
            except Exception as e:
                # close() refuses to bless a stuck engine thread; a
                # second close from main() would just block 30s more on
                # the same wedged thread
                print(f"[serve] drain failed: {e!r}", file=sys.stderr)
            finally:
                # buffered telemetry must land BEFORE the process goes
                # away: a SIGTERM'd replica used to rely on the main
                # thread's finally block alone, which a wedged drain
                # could starve — close here (idempotent; the atexit net
                # in obs/spans.py+obs/events.py is the last resort)
                _save_quality_fingerprint()
                if tracer is not None:
                    tracer.close()
                if events is not None:
                    events.emit("drained")
                    events.close()
                # the HTTP loop must stop regardless, or SIGTERM leaves
                # a zombie serving 503s forever
                drained["done"] = True
                httpd.shutdown()

        # a thread, because httpd.shutdown() deadlocks when called from
        # the serve_forever thread, and signal handlers must not block
        threading.Thread(target=_drain_then_stop, daemon=True).start()

    signal.signal(signal.SIGTERM, _graceful)
    print(
        f"[serve] {model_cfg.model} model, {serving.num_slots} slots — "
        f"POST http://{args.host}:{args.port}/generate, metrics at "
        f"GET http://{args.host}:{args.port}/metrics"
    )
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        if not drained["done"]:
            client.close()
        _save_quality_fingerprint()
        if tracer is not None:
            tracer.close()
            print(f"[serve] span trace written to {args.trace_path}")
        if events is not None:
            events.close()


if __name__ == "__main__":
    main()
