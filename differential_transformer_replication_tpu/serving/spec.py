"""Speculative-decoding drafters for the serving engine.

The paper's own experimental design — three interchangeable decoder
families trained on ONE tokenizer (models/control.py, diff.py,
ndiff.py) — is exactly the drafter/verifier pairing speculative
decoding needs (Leviathan et al. 2023, "Fast Inference from
Transformers via Speculative Decoding"): a cheap drafter proposes k
tokens per slot per iteration, and the target model verifies all k in
ONE fused multi-row pool step (models/decode.py:``forward_decode_spec``,
serving/engine.py:``_build_spec_step_fns``) instead of k sequential
decode steps. Every proposal is VERIFIED — an arbitrarily bad (or
poisoned) drafter can only cost throughput, never correctness: greedy
requests accept a draft token iff it equals the target's argmax
(bit-identical to non-spec greedy decoding), sampled requests run the
standard acceptance-ratio test under the existing per-request
``fold_in`` key chains.

Two drafter backends behind one interface:

- :class:`NGramDrafter` — the drafter-free prompt-lookup fallback: a
  host-side suffix map over each request's prompt + emitted tokens
  proposes the continuation that followed the most recent occurrence
  of the current n-gram suffix. Zero device cost; shines on the
  repetitive stretches (code, templated text, self-repeating greedy
  output) where lookups actually hit.
- :class:`ModelDrafter` — a small checkpoint (typically the control
  family beside a diff/ndiff target; any family sharing the tokenizer
  works) run greedily on its OWN contiguous slot-pool KV cache, params
  loaded beside the target's. The drafter pool mirrors the target's
  slot assignment 1:1; per-slot ``_next`` cursors track how far each
  slot's drafter cache is valid, so acceptance/rejection needs no
  explicit rollback — a rejected suffix simply leaves the cursor
  behind, and the next catch-up overwrites it (the same
  position-derived ring semantics the target uses). A poisoned drafter
  pool (the ``spec_drafter_crash`` fault) trips the same finite-logits
  reduction the engine's sampler uses; the drafter then REBUILDS its
  pool from params and returns no proposals, so the engine falls back
  to the non-spec decode step for that iteration — never garbage
  tokens, surfaced via ``serving_spec_drafter_crashes_total``.

Thread-safety: both drafters are lock-owning classes — the engine
thread mutates proposal/cursor/suffix-map state while /health handlers
and the bench read :meth:`stats` concurrently (graftlint GL301/GL6xx
machine-check the discipline, and tests/test_spec.py's mutation test
proves the check is not vacuous). Device work under the lock is fine:
only :meth:`stats` contends, and a scrape blocking for one tiny
drafter step is cheaper than torn counters.
"""

from __future__ import annotations

import threading
from functools import lru_cache
from typing import Dict, List, Optional, Sequence, Tuple

from differential_transformer_replication_tpu.serving.scheduler import (
    _pow2_chunk,
)


class DraftSlot:
    """One slot's proposal context, passed by the engine each
    iteration: the slot index, the FULL token history (cropped prompt
    + generated so far), the target position P of the last emitted
    token (history[P] is that token), and the per-slot draft cap the
    engine already clamped against max_new_tokens / the ring window /
    the request's own ``draft_len``."""

    __slots__ = ("index", "tokens", "pos", "cap")

    def __init__(self, index: int, tokens: Sequence[int], pos: int,
                 cap: int):
        self.index = index
        self.tokens = tokens
        self.pos = pos
        self.cap = cap


class _DrafterBase:
    """Shared counter surface; see the module docstring for why the
    lock exists (engine thread vs /health readers). Each concrete
    drafter assigns its OWN ``self._lock`` in ``__init__`` — graftlint
    GL301's lock-ownership analysis is per-class, and the machine
    check only guards classes that visibly own their lock."""

    kind = "none"

    def stats(self) -> dict:
        with self._lock:
            return {
                "kind": self.kind,
                "proposed_total": self._proposed,
                "drafter_crashes_total": self._crashes,
            }

    # interface ------------------------------------------------------

    def propose_all(self, slots: List[DraftSlot]) -> Dict[int, List[int]]:
        raise NotImplementedError

    def commit(self, index: int, new_pos: int) -> None:
        """The verify step emitted tokens for this slot; its last
        emitted token now sits at ``new_pos``. Default: nothing (the
        n-gram drafter re-reads history each round)."""

    def release(self, index: int) -> None:
        """The slot retired (finish/deadline/cancel)."""

    def reset(self) -> None:
        """Engine crash recovery: drop everything derived state."""


class NGramDrafter(_DrafterBase):
    """Prompt-lookup speculative decoding (drafter-free fallback).

    Per slot, a suffix map from every n-gram (n = ``max_n`` down to
    ``min_n``) of the request's token history to the position right
    after its most recent occurrence; a proposal is the continuation
    that followed the longest matching suffix of the current history.
    The map is built incrementally (each token indexes ``max_n`` keys),
    so per-iteration cost is O(new tokens), not O(history).
    """

    kind = "ngram"

    def __init__(self, max_n: int = 3, min_n: int = 1):
        if not (1 <= min_n <= max_n):
            raise ValueError(
                f"need 1 <= min_n <= max_n, got {min_n}..{max_n}"
            )
        self._lock = threading.Lock()
        self._proposed = 0
        self._crashes = 0
        self.max_n = max_n
        self.min_n = min_n
        # slot -> ({ngram tuple: (previous end, last end)},
        #           tokens indexed so far). Two ends per key because
        #          the history TAIL always matches itself at
        #          end == len(history) — the useful occurrence is the
        #          one before it.
        self._maps: Dict[int, Tuple[dict, int]] = {}

    def _index_locked(self, index: int, tokens: Sequence[int]):
        entry = self._maps.get(index)
        if entry is None or entry[1] > len(tokens):
            entry = ({}, 0)  # new occupant (slot reuse): fresh map
        smap, done = entry
        first = self.min_n if done == 0 else done + 1
        for end in range(first, len(tokens) + 1):
            for n in range(self.min_n, self.max_n + 1):
                if end - n >= 0:
                    key = tuple(tokens[end - n:end])
                    old = smap.get(key)
                    smap[key] = (old[1] if old else None, end)
        self._maps[index] = (smap, len(tokens))
        return smap

    def propose_all(self, slots: List[DraftSlot]) -> Dict[int, List[int]]:
        out: Dict[int, List[int]] = {}
        with self._lock:
            for s in slots:
                if s.cap <= 0:
                    continue
                # the engine passes history ending exactly at pos (the
                # common case) — avoid a per-iteration copy then
                hist = (
                    s.tokens if len(s.tokens) == s.pos + 1
                    else list(s.tokens[:s.pos + 1])
                )
                smap = self._index_locked(s.index, hist)
                prop: List[int] = []
                for n in range(min(self.max_n, len(hist)), self.min_n - 1,
                               -1):
                    ends = smap.get(tuple(hist[-n:]))
                    if ends is None:
                        continue
                    # the match ending AT the history tail proposes
                    # nothing (its continuation is the future); fall
                    # back to the occurrence before it
                    at = ends[1] if ends[1] < len(hist) else ends[0]
                    if at is not None:
                        prop = hist[at:at + s.cap]
                        break
                if prop:
                    out[s.index] = prop
                    self._proposed += len(prop)
        return out

    def release(self, index: int) -> None:
        with self._lock:
            self._maps.pop(index, None)

    def reset(self) -> None:
        with self._lock:
            self._maps.clear()


@lru_cache(maxsize=None)
def _drafter_step_fns(cfg, rope_len: int, k: int):
    """Jitted (prefill, k-round-propose) closures for the drafter's
    own slot pool — the drafter-side analog of the engine's
    ``_build_step_fns``, module-cached so drafter rebuilds after a
    crash (or fault) add ZERO recompiles. The propose closure runs ALL
    k greedy rounds as one fused program (k sequential dispatches per
    engine iteration were the dominant model-drafter cost on CPU),
    fusing the whole-pool forwards, the greedy argmaxes AND the
    finite-logits reduction: a poisoned drafter pool surfaces as a
    typed flag through exactly the guard the engine's sampler uses.
    Per-slot round caps ride as a runtime array — slots drop out of
    the masked merge as their caps fill, so varying caps recompile
    nothing."""
    import jax
    import jax.numpy as jnp

    from differential_transformer_replication_tpu.models.decode import (
        KV_CACHE_BATCH_AXIS,
        forward_chunk,
        forward_decode_pool,
        merge_cache_update,
    )

    def _prefill(params, cache, slot, tokens, pos):
        """One prompt/catch-up chunk for one drafter slot, in place in
        the pool (logits discarded — the drafter only needs the K/V)."""
        row = [
            {key: (c[key][:, slot][:, None]
                   if KV_CACHE_BATCH_AXIS[key] else c[key][slot][None])
             for key in c}
            for c in cache
        ]
        _, new_row = forward_chunk(
            params, tokens, pos, row, cfg, rope_len=rope_len
        )
        return [
            {key: (c[key].at[:, slot].set(nr[key][:, 0])
                   if KV_CACHE_BATCH_AXIS[key]
                   else c[key].at[slot].set(nr[key][0]))
             for key in c}
            for c, nr in zip(cache, new_row)
        ]

    def _propose(params, tokens0, pos0, caps, cache):
        """All k greedy propose rounds in one call: feed each slot's
        last token, take the argmax, feed it back — round r active
        for slot b iff r < caps[b]. Returns ((B, k) proposals,
        (B,) finite-ok over active rounds, updated cache)."""
        B = tokens0.shape[0]

        def body(r, carry):
            cache, cur_tok, cur_pos, out, ok = carry
            active = r < caps
            logits, new_cache = forward_decode_pool(
                params, cur_tok, cur_pos, cache, cfg,
                rope_len=rope_len,
            )
            lf = logits.astype(jnp.float32)
            nxt = jnp.argmax(lf, axis=-1).astype(jnp.int32)
            ok = ok & jnp.where(
                active, jnp.isfinite(lf).all(axis=-1), True
            )
            cache = merge_cache_update(active, new_cache, cache)
            out = out.at[:, r].set(jnp.where(active, nxt, 0))
            cur_tok = jnp.where(active, nxt, cur_tok)
            cur_pos = cur_pos + active.astype(jnp.int32)
            return cache, cur_tok, cur_pos, out, ok

        cache, _, _, out, ok = jax.lax.fori_loop(
            0, k, body,
            (cache, jnp.asarray(tokens0, jnp.int32),
             jnp.asarray(pos0, jnp.int32),
             jnp.zeros((B, k), jnp.int32),
             jnp.ones((B,), bool)),
        )
        return out, ok, cache

    donate = jax.default_backend() != "cpu"
    return (
        jax.jit(_prefill, donate_argnums=(1,) if donate else ()),
        jax.jit(_propose, donate_argnums=(4,) if donate else ()),
    )


class ModelDrafter(_DrafterBase):
    """A small checkpoint proposing greedily on its own slot pool.

    The drafter's contiguous KV pool mirrors the target's slot
    assignment 1:1. ``_next[i]`` is the first position of slot i whose
    drafter-cache entry is NOT yet valid for the slot's actual token
    history; catch-up (chunked, power-of-two ladder) feeds
    ``tokens[_next..P-1]`` before the k pooled propose rounds feed
    ``tokens[P]`` and then each argmax. :meth:`commit` rewinds the
    cursor past rejected rows — position arithmetic makes the stale
    suffix invisible, exactly like the target's ring.
    """

    kind = "model"

    def __init__(self, params: dict, cfg, num_slots: int, rope_len: int,
                 prefill_chunk: int = 128, draft_len: int = 4):
        import numpy as np

        from differential_transformer_replication_tpu.models.decode import (
            init_cache,
        )

        self._lock = threading.Lock()
        self._proposed = 0
        self._crashes = 0
        self._np = np
        self.params = params
        self.cfg = cfg
        self.num_slots = num_slots
        self.rope_len = max(rope_len, cfg.block_size)
        self.prefill_chunk = prefill_chunk
        self.draft_len = draft_len
        self._init_cache = lambda: init_cache(cfg, num_slots)
        self._prefill, self._propose = _drafter_step_fns(
            cfg, self.rope_len, draft_len
        )
        with self._lock:
            self.cache = self._init_cache()
            self._next = [0] * num_slots

    # -- drafter window: proposals must stay inside ITS ring too ------

    def window(self) -> int:
        return self.cfg.block_size

    def bytes_total(self) -> int:
        """HBM bytes the drafter pool holds beside the target's — the
        equal-HBM accounting term the README runbook works through."""
        with self._lock:
            return sum(
                leaf.nbytes for layer in self.cache
                for leaf in layer.values()
            )

    def poison(self) -> None:
        """Fault hook (``spec_drafter_crash@N``): NaN-poison the whole
        drafter pool so the next propose round's finite-logits
        reduction trips — proving the fall-back-to-non-spec path."""
        import jax.numpy as jnp

        with self._lock:
            self.cache = [
                {key: (jnp.full_like(leaf, jnp.nan)
                       if jnp.issubdtype(leaf.dtype, jnp.floating)
                       else jnp.zeros_like(leaf))
                 for key, leaf in layer.items()}
                for layer in self.cache
            ]

    def _rebuild_locked(self) -> None:
        self.cache = self._init_cache()  # graftlint: threadsafe (_locked helper: every caller holds self._lock)
        self._next = [0] * self.num_slots  # graftlint: threadsafe (_locked helper: every caller holds self._lock)

    def propose_all(self, slots: List[DraftSlot]) -> Dict[int, List[int]]:
        np = self._np
        import jax.numpy as jnp

        out: Dict[int, List[int]] = {}
        with self._lock:
            # catch-up: feed each slot the history tokens its cache
            # does not yet hold (positions _next..P-1), chunked on the
            # power-of-two ladder so only log2(prefill_chunk)+1 chunk
            # shapes ever compile (the engine's own ladder)
            for s in slots:
                start = self._next[s.index]
                while start < s.pos:
                    # the engine's own prefill ladder (one shared
                    # helper so drafter chunk shapes stay in lockstep
                    # with the scheduler's — the zero-recompile set)
                    size = _pow2_chunk(s.pos - start,
                                       self.prefill_chunk)
                    self.cache = self._prefill(
                        self.params, self.cache, np.int32(s.index),
                        jnp.asarray(
                            [list(s.tokens[start:start + size])],
                            jnp.int32,
                        ),
                        np.int32(start),
                    )
                    start += size
                self._next[s.index] = start
            # all k greedy rounds as ONE fused call (the jitted
            # fori_loop in _drafter_step_fns); per-slot caps ride as a
            # runtime array, so varying caps recompile nothing
            B = self.num_slots
            cur_tok = np.zeros((B,), np.int32)
            cur_pos = np.zeros((B,), np.int32)
            caps = np.zeros((B,), np.int32)
            proposing = []
            for s in slots:
                cap = min(s.cap, self.draft_len,
                          self.window() - s.pos - 1)
                if cap <= 0:
                    continue
                cur_tok[s.index] = s.tokens[s.pos]
                cur_pos[s.index] = s.pos
                caps[s.index] = cap
                proposing.append(s)
            if not proposing:
                return {}
            toks, ok, self.cache = self._propose(
                self.params, cur_tok, cur_pos, jnp.asarray(caps),
                self.cache,
            )
            toks = np.asarray(toks)
            ok = np.asarray(ok)
            if not all(bool(ok[s.index]) for s in proposing):
                # poisoned pool: the same finite-logits guard the
                # engine's sampler uses, surfaced typed — rebuild
                # from params, propose nothing, engine falls back to
                # the non-spec step (never garbage tokens; a drafted
                # garbage token would be rejected by the verify
                # anyway, but a dead drafter must not keep burning a
                # propose round per iteration)
                self._crashes += 1
                self._rebuild_locked()
                return {}
            for s in proposing:
                cap = int(caps[s.index])
                out[s.index] = [int(t) for t in toks[s.index, :cap]]
                self._next[s.index] = s.pos + cap
                self._proposed += cap
        return out

    def commit(self, index: int, new_pos: int) -> None:
        """Rewind the slot's validity cursor past rejected rows: cache
        entries at positions >= new_pos hold rejected draft K/V and
        must be re-fed (the accepted prefix below new_pos is valid by
        construction — the drafter fed exactly those tokens)."""
        with self._lock:
            self._next[index] = min(self._next[index], new_pos)

    def release(self, index: int) -> None:
        with self._lock:
            self._next[index] = 0

    def reset(self) -> None:
        """Engine crash recovery: fresh pool from params (zero
        recompiles — the jitted closures are module-cached)."""
        with self._lock:
            self._rebuild_locked()


def build_drafter(serving, target_cfg, rope_len: int,
                  drafter: Optional[Tuple[dict, object]] = None):
    """Construct the configured drafter for an engine.

    ``drafter`` is an optional pre-loaded ``(params, cfg)`` pair
    (tests, sample.py); otherwise ``spec_mode == "model"`` loads
    ``spec_drafter_ckpt`` through the SAME
    ``load_params_for_inference`` path as the target — manifest
    verification included. A drafter whose vocab differs from the
    target's cannot share the tokenizer and fails loudly."""
    if not serving.spec_enabled():
        return None
    if serving.spec_mode == "ngram":
        return NGramDrafter()
    if drafter is not None:
        d_params, d_cfg = drafter
    else:
        if not serving.spec_drafter_ckpt:
            raise ValueError(
                "spec_mode='model' needs spec_drafter_ckpt (or a "
                "pre-loaded drafter)"
            )
        from differential_transformer_replication_tpu.train.checkpoint import (
            load_params_for_inference,
        )

        d_params, d_cfg, _ = load_params_for_inference(
            serving.spec_drafter_ckpt
        )
    if d_cfg.vocab_size != target_cfg.vocab_size:
        raise ValueError(
            f"drafter vocab ({d_cfg.vocab_size}) != target vocab "
            f"({target_cfg.vocab_size}): drafter and target must share "
            "one tokenizer"
        )
    # the drafter inherits the target's serving-side decode overrides
    # only where they apply to ITS config; its own checkpoint settings
    # otherwise stand (a bf16 drafter beside an int8 target is fine —
    # proposals are token ids, not activations)
    return ModelDrafter(
        d_params, d_cfg, serving.num_slots, rope_len,
        prefill_chunk=serving.prefill_chunk,
        draft_len=serving.spec_draft_len,
    )


def constrain_proposals(props: Dict[int, List[int]],
                        fsms: Dict[int, tuple]) -> Dict[int, List[int]]:
    """Truncate drafter proposals at the first token a slot's
    constraint FSM disallows (serving/constrain.py:TokenFsm).

    ``fsms`` maps slot index -> (fsm, current state) for constrained
    slots; unconstrained slots pass through untouched. A draft the FSM
    rejects outright is dropped (the slot rides the verify step with
    draft length 0 — a runtime array, no recompile). Truncation is an
    OPTIMIZATION, not a correctness requirement: the verify step's
    accept compares each draft token against the argmax/draw of the
    constraint-MASKED target logits, so a disallowed draft token is
    always rejected anyway — pre-truncating just stops the drafter
    from burning verify rows it can never win (Leviathan's
    distribution-preservation is untouched either way)."""
    if not fsms:
        return props
    out: Dict[int, List[int]] = {}
    for i, toks in props.items():
        ent = fsms.get(i)
        if ent is not None:
            fsm, state = ent
            toks = toks[:fsm.prefix_len(toks, state=state)]
        if toks:
            out[i] = toks
    return out
