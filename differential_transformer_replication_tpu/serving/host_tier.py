"""Host-RAM KV page tier: graceful degradation under HBM page pressure.

The paged pool (serving/pages.py) has one pressure valve — LRU eviction
of unreferenced radix leaves — and eviction is PERMANENT: the prefix's
KV is gone and the next request that needs it pays a full recompute.
This module adds the second tier of the hierarchy (the vLLM/SGLang
swap-out shape, Kwon et al. 2023): evicted pages DEMOTE into pinned
host buffers here instead of vanishing, and a later admission that
matches a demoted prefix PROMOTES it back with a host->device copy —
a copy, never a recompute. int8 KV pages (~0.53x bf16 bytes) make a
few GB of host RAM hold ~50x the HBM pool.

Two kinds of entry share one byte budget (``budget_bytes``):

- **Cached prefixes** (:meth:`put` / :meth:`get`), keyed by the full
  token prefix a page covers. LRU-evicted when the budget is exceeded
  — the tier is a cache; losing an entry costs a recompute, never
  correctness.
- **Stashes** (:meth:`stash` / :meth:`unstash`), keyed by an opaque
  tag (the engine uses request ids): the page images of a PREEMPTED
  request mid-decode. Stashes are byte-accounted but NEVER evicted —
  they are correctness state, not cache — so a burst of preemptions
  may overshoot the budget (cached entries are evicted first to make
  room; the overshoot is visible on the ``bytes`` gauge).

Every payload is checksummed (CRC32 over the raw leaf bytes) at
insertion and verified at retrieval: a torn or corrupted host copy is
detected and counted (``corrupt_total``), surfaces as a MISS, and the
engine degrades to recompute — never a garbage token (the
``page_swap_corrupt`` fault in utils/faults.py drills exactly this).

Payloads are opaque to this module: per-layer dicts of host numpy
arrays (one physical page's K/V leaves, models/decode.py layout).
Nothing here imports jax — device transfers are the engine's job;
this is pure locked host bookkeeping, like the page pool itself.

Lock order (graftlint GL601): PagePool._lock -> HostTier._lock. The
pool consults the tier while planning an admission (under its own
lock); the tier NEVER calls back into the pool.
"""

from __future__ import annotations

import threading
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple


def payload_nbytes(payload: List[dict]) -> int:
    """Host bytes of one page payload (per-layer leaf dicts)."""
    return sum(arr.nbytes for layer in payload for arr in layer.values())


def payload_checksum(payload: List[dict]) -> int:
    """CRC32 over every leaf's raw bytes, in canonical (layer, sorted
    key) order — the torn-copy detector both tiers of the hierarchy
    verify against."""
    crc = 0
    for layer in payload:
        for key in sorted(layer):
            crc = zlib.crc32(layer[key].tobytes(), crc)
    return crc


class TierEntry:
    """One stored page image: the payload, its byte size, and the
    CRC32 stamped at insertion (verified at every retrieval)."""

    __slots__ = ("payload", "nbytes", "checksum")

    def __init__(self, payload: List[dict]):
        self.payload = payload
        self.nbytes = payload_nbytes(payload)
        self.checksum = payload_checksum(payload)

    def verify(self) -> bool:
        return payload_checksum(self.payload) == self.checksum


class HostTier:
    """Byte-budgeted host-RAM page store (module docstring).

    All mutable state is guarded by ``self._lock``: the engine thread
    demotes/promotes while /health handlers and the bench read
    :meth:`stats` concurrently. Nothing blocking ever runs under the
    lock (graftlint GL602) — payload copies happen in the caller."""

    def __init__(self, *, budget_bytes: int):
        if budget_bytes < 1:
            raise ValueError(
                f"budget_bytes must be >= 1, got {budget_bytes}"
            )
        self.budget_bytes = budget_bytes
        self._lock = threading.Lock()
        with self._lock:
            self._entries: "OrderedDict[tuple, TierEntry]" = OrderedDict()  # graftlint: threadsafe (guarded by self._lock)
            self._stashes: Dict[object, List[TierEntry]] = {}  # graftlint: threadsafe (guarded by self._lock)
            self._cached_bytes = 0  # graftlint: threadsafe (guarded by self._lock)
            self._stash_bytes = 0  # graftlint: threadsafe (guarded by self._lock)
            self._hits = 0  # graftlint: threadsafe (guarded by self._lock)
            self._misses = 0  # graftlint: threadsafe (guarded by self._lock)
            self._evictions = 0  # graftlint: threadsafe (guarded by self._lock)
            self._corrupt = 0  # graftlint: threadsafe (guarded by self._lock)
            self._rejected = 0  # graftlint: threadsafe (guarded by self._lock)

    # -- cached prefixes ----------------------------------------------

    def put(self, key: tuple, payload: List[dict]) -> bool:
        """Demote one page image under ``key`` (the full token prefix
        it covers). LRU-evicts older cached entries to fit the budget;
        returns False (counted ``rejected_total``) when the payload
        cannot fit even with every cached entry evicted — stashes are
        pinned and never make way for a cache insert."""
        ent = TierEntry(payload)
        with self._lock:
            if ent.nbytes + self._stash_bytes > self.budget_bytes:
                self._rejected += 1
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self._cached_bytes -= old.nbytes
            self._evict_until_locked(ent.nbytes)
            self._entries[key] = ent
            self._cached_bytes += ent.nbytes
            return True

    def get(self, key: tuple) -> Optional[TierEntry]:
        """The cached entry for ``key``, LRU-refreshed — or None on a
        miss. A checksum mismatch (torn/corrupted host copy) drops the
        entry, counts ``corrupt_total``, and reads as a miss: the
        caller recomputes, it never injects garbage KV."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self._misses += 1
                return None
            if not ent.verify():
                del self._entries[key]
                self._cached_bytes -= ent.nbytes
                self._corrupt += 1
                self._misses += 1
                return None
            self._entries.move_to_end(key)
            self._hits += 1
            return ent

    def _evict_until_locked(self, incoming: int) -> None:
        while (self._cached_bytes + self._stash_bytes + incoming
               > self.budget_bytes and self._entries):
            _, old = self._entries.popitem(last=False)
            self._cached_bytes -= old.nbytes  # graftlint: threadsafe (_locked helper: every caller holds self._lock)
            self._evictions += 1  # graftlint: threadsafe (_locked helper: every caller holds self._lock)

    # -- preemption stashes -------------------------------------------

    def stash(self, tag, payloads: List[List[dict]]) -> None:
        """Pin a preempted request's page images under ``tag``. Never
        refused and never evicted — this is the request's decode state,
        not a cache; cached entries are evicted to make room, and a
        stash burst may overshoot the budget (visible on the gauges)."""
        ents = [TierEntry(p) for p in payloads]
        nbytes = sum(e.nbytes for e in ents)
        with self._lock:
            old = self._stashes.pop(tag, None)
            if old is not None:
                self._stash_bytes -= sum(e.nbytes for e in old)
            self._evict_until_locked(nbytes)
            self._stashes[tag] = ents
            self._stash_bytes += nbytes

    def unstash(self, tag) -> Optional[List[TierEntry]]:
        """Pop (and return) the stash under ``tag``; None when absent.
        The caller verifies each entry's checksum at injection time —
        a mismatch there degrades to a full restart, bit-exact under
        the per-request fold_in key chains."""
        with self._lock:
            ents = self._stashes.pop(tag, None)
            if ents is not None:
                self._stash_bytes -= sum(e.nbytes for e in ents)
            return ents

    def drop_stash(self, tag) -> None:
        """Discard a stash (cancelled/expired/crashed request) so its
        pinned bytes return to the budget."""
        with self._lock:
            ents = self._stashes.pop(tag, None)
            if ents is not None:
                self._stash_bytes -= sum(e.nbytes for e in ents)

    def note_corrupt(self, n: int = 1) -> None:
        """Count a corruption the CALLER detected (stash checksum
        verified at injection time, outside the tier's lock)."""
        with self._lock:
            self._corrupt += n

    # -- lifecycle ----------------------------------------------------

    def clear_cache(self) -> None:
        """Drop every CACHED entry, keep stashes. The crash-recovery
        path: after an engine crash every cached prefix is untrusted
        (a poisoned device page may have been demoted here), but
        stashes remain valid — they hold host copies of a preempted
        request's state, and preempted requests survive a crash in the
        preserved queue. Monotonic counters survive."""
        with self._lock:
            self._entries.clear()
            self._cached_bytes = 0

    # -- telemetry ----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "budget_bytes": self.budget_bytes,
                "bytes": self._cached_bytes + self._stash_bytes,
                "cached_bytes": self._cached_bytes,
                "stash_bytes": self._stash_bytes,
                "entries": len(self._entries),
                "stashes": len(self._stashes),
                "hits_total": self._hits,
                "misses_total": self._misses,
                "evictions_total": self._evictions,
                "corrupt_total": self._corrupt,
                "rejected_total": self._rejected,
            }
