"""Static analysis + runtime sanitizers for the JAX hot paths.

- :mod:`.rules` / :mod:`.lint` — the ``graftlint`` AST engine: JAX
  hazard rules (host syncs, impure calls, recompile triggers, missing
  donation, serving lock discipline) over the package's source. Pure
  stdlib; importing them never imports jax.
- :mod:`.sanitizers` — dynamic counterparts: a recompile sentinel that
  counts real XLA compilations against a budget and a host-sync
  sentinel over ``jax.transfer_guard``. Imports jax, so it is exposed
  lazily here (PEP 562) — ``graftlint`` stays runnable on boxes where
  jax cannot initialize.

CLI: ``python tools/graftlint.py <paths>`` or the ``graftlint``
console script (analysis/cli.py). Catalog + suppression syntax:
ANALYSIS.md.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from differential_transformer_replication_tpu.analysis.lint import (
    Finding,
    LintResult,
    lint_paths,
    to_sarif,
)
from differential_transformer_replication_tpu.analysis.rules import (
    RULES,
    RULES_BY_ID,
    Rule,
)

if TYPE_CHECKING:  # pragma: no cover — static analyzers only
    from differential_transformer_replication_tpu.analysis.sanitizers import (  # noqa: F401
        HostSyncError,
        HostSyncSentinel,
        RecompileBudgetError,
        RecompileSentinel,
        compile_count,
    )

_LAZY = {
    "RecompileSentinel", "RecompileBudgetError", "HostSyncSentinel",
    "HostSyncError", "compile_count",
}

__all__ = [
    "Finding", "LintResult", "lint_paths", "to_sarif", "Rule", "RULES",
    "RULES_BY_ID", *sorted(_LAZY),
]


def __getattr__(name: str):
    if name in _LAZY:
        from differential_transformer_replication_tpu.analysis import (
            sanitizers,
        )

        return getattr(sanitizers, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )
