"""The ``graftlint`` command line: lint paths, report, gate CI.

Exit status: 0 when no ACTIVE (unsuppressed) findings, 1 otherwise,
2 on usage errors. ``--json`` prints one machine-parseable JSON object
(stable key order, findings sorted by path/line/rule) — what
tests/test_lint_clean.py and any CI gate consume. Suppressed findings
are reported either way so a suppression stays an auditable decision.

Examples::

    graftlint differential_transformer_replication_tpu/
    graftlint --json pkg/ | python -m json.tool
    graftlint --rules GL101,GL202 pkg/train/trainer.py
    graftlint --list-rules
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

from differential_transformer_replication_tpu.analysis.lint import (
    _iter_py_files,
    lint_paths,
)
from differential_transformer_replication_tpu.analysis.rules import (
    RULES,
    RULES_BY_ID,
    resolve_rule_token,
)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="JAX hazard linter: host syncs, impure jit regions, "
                    "recompile triggers, missing donation, serving lock "
                    "discipline. Rule catalog: ANALYSIS.md.",
    )
    p.add_argument("paths", nargs="*",
                   help="files or directories to lint")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-parseable JSON report on stdout")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids/names to run "
                        "(default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print suppressed findings in text mode")
    args = p.parse_args(argv)

    if args.list_rules:
        for r in RULES:
            print(f"{r.id} {r.name}\n    {r.summary}\n    hint: {r.hint}")
        return 0
    if not args.paths:
        p.print_usage(sys.stderr)
        print("graftlint: error: no paths given", file=sys.stderr)
        return 2

    rules = (
        [t for t in args.rules.split(",") if t.strip()]
        if args.rules else None
    )
    if rules:
        # a typoed rule id would otherwise lint NOTHING and exit 0 —
        # a misconfigured CI gate must fail loudly, not pass forever
        unknown = [
            t for t in rules if resolve_rule_token(t) not in RULES_BY_ID
        ]
        if unknown:
            print(
                f"graftlint: error: unknown rule(s) {', '.join(unknown)} "
                "(see --list-rules)", file=sys.stderr,
            )
            return 2

    # like the unknown-rule guard: a typoed/renamed path would lint
    # NOTHING and exit 0 — a gate that scans zero files must fail
    # loudly, not pass forever
    enumerated = []
    for path in args.paths:
        if not os.path.exists(path):
            print(f"graftlint: error: path does not exist: {path}",
                  file=sys.stderr)
            return 2
        found = _iter_py_files([path])
        if not found:
            print(f"graftlint: error: no .py files under: {path}",
                  file=sys.stderr)
            return 2
        enumerated.extend(found)
    result = lint_paths(args.paths, rules=rules, files=enumerated)

    if args.as_json:
        print(json.dumps(result.as_dict(), sort_keys=False))
    else:
        shown = (
            result.findings if args.show_suppressed else result.active
        )
        for f in shown:
            print(f.render())
        for rel in result.parse_errors:
            print(f"{rel}: parse error — file skipped (every rule "
                  "silently exempt)", file=sys.stderr)
        n_sup = len(result.findings) - len(result.active)
        print(
            f"graftlint: {result.files_scanned} files, "
            f"{result.jit_regions} jit-region functions, "
            f"{len(result.active)} finding(s)"
            + (f" (+{n_sup} suppressed)" if n_sup else "")
            + (f", {len(result.parse_errors)} parse error(s)"
               if result.parse_errors else ""),
            file=sys.stderr,
        )
    return 1 if result.active or result.parse_errors else 0


if __name__ == "__main__":
    sys.exit(main())
