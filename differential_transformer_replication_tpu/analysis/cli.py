"""The ``graftlint`` command line: lint paths, report, gate CI.

Exit status: 0 when no ACTIVE (unsuppressed) error-severity findings,
1 otherwise, 2 on usage errors. Warn-severity findings (GL503) are
reported but never flip the exit code.

Output formats (``--format``, default ``text``):

- ``json`` (alias ``--json``): one machine-parseable JSON object
  (stable key order, findings sorted by path/line/rule) — what
  tests/test_lint_clean.py and any CI gate consume.
- ``sarif``: a SARIF 2.1.0 document so CI (GitHub code scanning and
  friends) can annotate findings inline — schema-pinned and
  deterministic exactly like the JSON.

``--changed <ref>`` lints only files modified vs a git ref (committed,
staged, working-tree, or untracked) while the call graph is still
built from the WHOLE tree, so cross-module jit-region reachability and
axis environments stay sound — pre-commit latency stays flat as the
tree grows. Parse errors anywhere still fail the gate (an unparseable
file is silently rule-exempt no matter which files changed).

Suppressed findings are reported either way so a suppression stays an
auditable decision.

Examples::

    graftlint differential_transformer_replication_tpu/
    graftlint --json pkg/ | python -m json.tool
    graftlint --format sarif pkg/ > graftlint.sarif
    graftlint --changed origin/main pkg/
    graftlint --rules GL101,GL202 pkg/train/trainer.py
    graftlint --list-rules
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Optional, Sequence, Set

from differential_transformer_replication_tpu.analysis.lint import (
    DEFAULT_VMEM_BUDGET_MIB,
    _iter_py_files,
    lint_paths,
    to_sarif,
)
from differential_transformer_replication_tpu.analysis.rules import (
    RULES,
    RULES_BY_ID,
    resolve_rule_token,
)


def _git_changed_files(ref: str, anchor: str) -> Optional[Set[str]]:
    """Absolute paths of files changed vs ``ref`` in the repo that
    contains ``anchor``: committed+staged+working diffs plus untracked
    files (a brand-new hazard file must not dodge a changed-files
    gate). None when git fails (caller reports the usage error)."""
    anchor_dir = anchor if os.path.isdir(anchor) else os.path.dirname(anchor)
    try:
        top = subprocess.run(
            ["git", "-C", anchor_dir or ".", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, check=True,
        ).stdout.strip()
        diff = subprocess.run(
            ["git", "-C", top, "diff", "--name-only", ref, "--"],
            capture_output=True, text=True, check=True,
        ).stdout.splitlines()
        untracked = subprocess.run(
            ["git", "-C", top, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, check=True,
        ).stdout.splitlines()
    except (OSError, subprocess.CalledProcessError):
        return None
    # realpath on BOTH sides of the later comparison: git reports the
    # PHYSICAL toplevel, while lint paths may reach the repo through a
    # symlink — abspath-vs-physical mismatch would silently filter
    # every finding and pass the gate
    return {
        os.path.realpath(os.path.join(top, rel))
        for rel in diff + untracked if rel.strip()
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="JAX hazard linter: host syncs, impure jit regions, "
                    "recompile triggers, missing donation, collective/"
                    "sharding discipline, Pallas kernel checks, lock-order "
                    "analysis. Rule catalog: ANALYSIS.md.",
    )
    p.add_argument("paths", nargs="*",
                   help="files or directories to lint")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default=None, dest="fmt",
                   help="output format (default: text)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="alias for --format json")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids/names to run "
                        "(default: all)")
    p.add_argument("--changed", default=None, metavar="REF",
                   help="report findings only for files changed vs this "
                        "git ref (call graph still spans the whole tree)")
    p.add_argument("--vmem-budget", type=float,
                   default=DEFAULT_VMEM_BUDGET_MIB, metavar="MIB",
                   help="GL503 VMEM footprint budget in MiB "
                        f"(default {DEFAULT_VMEM_BUDGET_MIB:g})")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print suppressed findings in text mode")
    args = p.parse_args(argv)

    if args.fmt and args.as_json and args.fmt != "json":
        print("graftlint: error: --json conflicts with "
              f"--format {args.fmt}", file=sys.stderr)
        return 2
    fmt = args.fmt or ("json" if args.as_json else "text")

    if args.list_rules:
        for r in RULES:
            sev = "" if r.severity == "error" else f" [{r.severity}]"
            print(f"{r.id} {r.name}{sev}\n    {r.summary}\n"
                  f"    hint: {r.hint}")
        return 0
    if not args.paths:
        p.print_usage(sys.stderr)
        print("graftlint: error: no paths given", file=sys.stderr)
        return 2

    rules = (
        [t for t in args.rules.split(",") if t.strip()]
        if args.rules else None
    )
    if rules:
        # a typoed rule id would otherwise lint NOTHING and exit 0 —
        # a misconfigured CI gate must fail loudly, not pass forever
        unknown = [
            t for t in rules if resolve_rule_token(t) not in RULES_BY_ID
        ]
        if unknown:
            print(
                f"graftlint: error: unknown rule(s) {', '.join(unknown)} "
                "(see --list-rules)", file=sys.stderr,
            )
            return 2

    # like the unknown-rule guard: a typoed/renamed path would lint
    # NOTHING and exit 0 — a gate that scans zero files must fail
    # loudly, not pass forever
    enumerated = []
    for path in args.paths:
        if not os.path.exists(path):
            print(f"graftlint: error: path does not exist: {path}",
                  file=sys.stderr)
            return 2
        found = _iter_py_files([path])
        if not found:
            print(f"graftlint: error: no .py files under: {path}",
                  file=sys.stderr)
            return 2
        enumerated.extend(found)

    changed_abs: Optional[Set[str]] = None
    if args.changed is not None:
        changed_abs = _git_changed_files(args.changed, args.paths[0])
        if changed_abs is None:
            print(f"graftlint: error: git diff against {args.changed!r} "
                  "failed (not a git checkout, or unknown ref)",
                  file=sys.stderr)
            return 2

    result = lint_paths(args.paths, rules=rules, files=enumerated,
                        vmem_budget_mib=args.vmem_budget)

    if changed_abs is not None:
        # filter by the lint enumeration's ABSOLUTE paths (display
        # relpaths keep only one parent component and may collide)
        keep_rel = {
            rel for full, rel, _mod in enumerated
            if os.path.realpath(full) in changed_abs
        }
        result.findings = [
            f for f in result.findings if f.path in keep_rel
        ]

    if fmt == "json":
        doc = result.as_dict()
        if args.changed is not None:
            doc["changed_vs"] = args.changed
        print(json.dumps(doc, sort_keys=False))
    elif fmt == "sarif":
        print(json.dumps(to_sarif(result), sort_keys=False))
    else:
        shown = (
            result.findings if args.show_suppressed else result.active
        )
        for f in shown:
            print(f.render())
        for rel in result.parse_errors:
            print(f"{rel}: parse error — file skipped (every rule "
                  "silently exempt)", file=sys.stderr)
        n_sup = len(result.findings) - len(result.active)
        n_warn = len(result.active) - len(result.gating)
        print(
            f"graftlint: {result.files_scanned} files, "
            f"{result.jit_regions} jit-region functions, "
            f"{len(result.gating)} finding(s)"
            + (f" (+{n_warn} warning)" if n_warn else "")
            + (f" (+{n_sup} suppressed)" if n_sup else "")
            + (f", {len(result.parse_errors)} parse error(s)"
               if result.parse_errors else ""),
            file=sys.stderr,
        )
    return 1 if result.gating or result.parse_errors else 0


if __name__ == "__main__":
    sys.exit(main())
