"""The graftlint rule catalog.

Every rule is a named, documented invariant of this codebase's JAX hot
paths — the things the ROADMAP asserts in prose ("zero recompiles as
requests come and go", "one jitted full-pool decode step", "no exposed
host syncs in the timed window") turned into machine-checked facts.
The AST engine (analysis/lint.py) walks the package, classifies each
function as inside or outside a *jit region* (reachable from a
``jax.jit`` / ``pmap`` / ``vmap`` / ``lax.scan``-style tracing root
through the call graph), and dispatches the checks below.

Rule id families:

- ``GL1xx`` — checks that apply INSIDE jit regions (the traced code a
  compiled XLA program is built from).
- ``GL2xx`` — checks on how jitted entry points are built and driven
  from host code (donation, step-loop sync discipline).
- ``GL3xx`` — thread-discipline checks for the serving layer (host
  threads sharing one engine).
- ``GL4xx`` — sharding/collective discipline: named-axis collectives
  must be reachable from an axis-binding context (``shard_map`` /
  ``pmap``), must not hide under per-shard-divergent control flow, and
  shard bodies must stay free of host transfers.
- ``GL5xx`` — Pallas kernel checks at ``pallas_call`` sites and inside
  kernel bodies: grid/BlockSpec divisibility, fp32-accumulation,
  VMEM-footprint estimation (warn-level), kernel purity/closures.
- ``GL6xx`` — concurrency checks over lock-owning classes (serving/,
  tools/fleet.py, and anywhere else a class owns a lock): a lock-order
  graph catches A→B / B→A inversions, and blocking calls while holding
  a lock are flagged.

Severity: every rule is ``error`` (gates CI) except where noted
``warning`` (reported, never flips the exit code) — currently GL503,
whose VMEM estimate is a model, not a measurement.

Suppressions (analysis/lint.py parses them from comments):

- ``# graftlint: disable=GL101`` — suppress listed rule ids (or rule
  names) on this line / this statement.
- ``# graftlint: threadsafe`` — alias for ``disable=GL301``; the
  documented marker for attributes that are mutated cross-thread by
  design (e.g. monotonic floats safely published via the GIL).
- ``# graftlint: disable-file=GL105,GL106`` — suppress for the whole
  file (``disable-file`` alone disables every rule).

Adding a rule: append a :class:`Rule` here with a fresh id in the
right family, implement its check in analysis/lint.py (grep for the
rule id — each id has exactly one emit site), and add a positive +
negative + suppressed fixture to tests/test_analysis/test_rules.py.
ANALYSIS.md carries the human-readable catalog; keep it in sync.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple


@dataclass(frozen=True)
class Rule:
    id: str
    name: str
    summary: str
    hint: str
    severity: str = "error"  # "error" gates CI; "warning" is advisory


RULES: Tuple[Rule, ...] = (
    Rule(
        id="GL101",
        name="host-sync-in-jit",
        summary=(
            "Blocking device->host transfer inside a jit region: "
            ".item() / .tolist() / .block_until_ready() / "
            "jax.device_get() / np.asarray() on a traced value. Under "
            "trace these either fail or silently bake a concrete value "
            "into the compiled program; on the hot path they serialize "
            "the device pipeline."
        ),
        hint=(
            "Keep the value on device (jnp ops) and return it; sync "
            "once at the host boundary, outside the jitted function."
        ),
    ),
    Rule(
        id="GL102",
        name="host-cast-in-jit",
        summary=(
            "float()/int()/bool() applied to a traced value inside a "
            "jit region — concretizes the tracer (TracerConversionError "
            "at best, a silent trace-time constant at worst)."
        ),
        hint=(
            "Use jnp.astype / lax.convert_element_type for dtype "
            "changes, jnp.where / lax.cond for value-dependent logic."
        ),
    ),
    Rule(
        id="GL103",
        name="impure-call-in-jit",
        summary=(
            "Impure call inside a jit region (time.*, random.*, "
            "np.random.*, print/open/input, logging, os.environ). It "
            "runs ONCE at trace time and its result is frozen into the "
            "compiled program — wall clocks stop ticking, host RNG "
            "stops advancing, logs fire once per compile, not per step."
        ),
        hint=(
            "Thread randomness through jax.random keys; move clocks, "
            "I/O and logging to the host loop around the jitted call "
            "(or jax.debug.print / io_callback when it must be inside)."
        ),
    ),
    Rule(
        id="GL104",
        name="traced-branch",
        summary=(
            "Python `if`/`while`/`assert` on a traced value inside a "
            "jit region. Either it raises TracerBoolConversionError, "
            "or — when the operand happens to be concrete at trace "
            "time — it silently becomes a shape/value-specialized "
            "recompile trigger."
        ),
        hint=(
            "Use jnp.where for selects, lax.cond / lax.select for "
            "branches, lax.while_loop for loops on traced values."
        ),
    ),
    Rule(
        id="GL105",
        name="fstring-in-jit",
        summary=(
            "String formatting (f-string / str() of a runtime value) "
            "inside a jit region, outside raise/assert. Formatting a "
            "tracer concretizes it, and shape-dependent strings passed "
            "as static args force one recompile per distinct string."
        ),
        hint=(
            "Format on the host after the sync point; for in-trace "
            "debugging use jax.debug.print. (Messages inside `raise` / "
            "`assert` run at trace time on static data and are exempt.)"
        ),
    ),
    Rule(
        id="GL106",
        name="set-iteration-in-jit",
        summary=(
            "Iteration over a set inside a jit region. Set order "
            "depends on hashes (and for str keys on interpreter hash "
            "randomization), so the traced op order — and any pytree "
            "built from it — can differ between processes: collective "
            "mismatches on pods, cache misses across restarts."
        ),
        hint=(
            "Iterate a sorted() or a tuple/list with a fixed order; "
            "pytrees keyed by dicts are fine (insertion order)."
        ),
    ),
    Rule(
        id="GL107",
        name="global-state-in-jit",
        summary=(
            "`global`/`nonlocal` rebinding inside a jit region. The "
            "write happens once at trace time, and rebinding a name to "
            "a tracer leaks it out of the trace — a classic source of "
            "UnexpectedTracerError far from the cause."
        ),
        hint=(
            "Return the value from the jitted function and rebind on "
            "the host; carry loop state through scan/while_loop "
            "carries."
        ),
    ),
    Rule(
        id="GL201",
        name="missing-donate",
        summary=(
            "A step/decode/prefill/update entry point is jitted "
            "without donate_argnums/donate_argnames. State-in/state-"
            "out calls that do not donate keep TWO copies of the "
            "train state / KV pool live across the call — roughly "
            "doubling peak HBM for the update."
        ),
        hint=(
            "jax.jit(fn, donate_argnums=(0,)) (the state argument); "
            "decorator form: @partial(jax.jit, donate_argnums=(0,)). "
            "Suppress for genuinely non-consuming entry points."
        ),
    ),
    Rule(
        id="GL202",
        name="sync-in-step-loop",
        summary=(
            "Blocking device->host sync (float()/int()/.item()/"
            "jax.device_get) inside a loop that dispatches a jitted "
            "step. Every sync stalls the host until the device "
            "catches up, breaking async-dispatch pipelining — the "
            "difference between overlapped and serialized step time."
        ),
        hint=(
            "Batch the fetches (ONE jax.device_get of a tuple), "
            "amortize over an interval (log/eval cadence), and mark "
            "deliberate sync points with a suppression explaining the "
            "cadence."
        ),
    ),
    Rule(
        id="GL301",
        name="unguarded-shared-mutation",
        summary=(
            "serving/: an attribute of a lock-owning class is mutated "
            "outside `with self.<lock>` while other methods also touch "
            "it. The HTTP handler threads and the engine loop share "
            "these objects; unguarded read-modify-writes tear, and "
            "even plain stores can publish half-updated state to "
            "/health readers."
        ),
        hint=(
            "Mutate under the class's lock/condition, or — for "
            "deliberately lock-free monotonic publishes — annotate the "
            "line with `# graftlint: threadsafe` and say why."
        ),
    ),
    Rule(
        id="GL401",
        name="unbound-collective-axis",
        summary=(
            "A named-axis collective (psum/pmean/pmax/pmin/all_gather/"
            "ppermute/all_to_all/axis_index/axis_size) in a function "
            "not reachable from any shard_map/pmap axis-binding "
            "context — or naming an axis the reachable contexts "
            "provably do not bind. At trace time that is an unbound "
            "axis-name error; worse, code that LOOKS collective but "
            "never runs under a mesh silently computes shard-local "
            "garbage when later jitted directly."
        ),
        hint=(
            "Call the function from (or wrap it in) shard_map/pmap "
            "binding that axis, or thread the axis name in from the "
            "binding site. If the engine cannot see your binding path "
            "(e.g. a registry of callbacks), suppress with the path "
            "spelled out in the reason."
        ),
    ),
    Rule(
        id="GL402",
        name="collective-under-traced-branch",
        summary=(
            "A collective reachable from a `lax.cond`/`lax.switch` "
            "branch or `lax.while_loop` body. Branch predicates and "
            "loop trip counts are traced values that can DIVERGE "
            "per shard — one shard enters the collective while its "
            "peers skip it, and the program deadlocks (multihost: "
            "until the barrier timeout kills the pod)."
        ),
        hint=(
            "Hoist the collective out of the branch, or reduce the "
            "predicate to a provably-uniform scalar FIRST (pmean/psum "
            "it, the pattern train/step.py uses for the anomaly "
            "guard) and suppress with the uniformity argument as the "
            "reason."
        ),
    ),
    Rule(
        id="GL403",
        name="host-transfer-in-shard-body",
        summary=(
            "jax.device_put (an explicit host->device placement) "
            "inside a shard_map/pmap body. Per-shard code runs under "
            "SPMD lowering; a device_put there either fails to trace "
            "or bakes one device's placement into every shard's "
            "program — and any host round-trip serializes all shards."
        ),
        hint=(
            "Place operands BEFORE the shard_map call site (in_specs "
            "already describe the placement); inside the body use "
            "jnp ops only."
        ),
    ),
    Rule(
        id="GL501",
        name="pallas-grid-mismatch",
        summary=(
            "pallas_call whose out_shape dimension is provably not "
            "divisible by the corresponding out_specs BlockSpec block "
            "dimension (both statically known at the call site). "
            "Mosaic pads the ragged tail tile; reads of the pad are "
            "garbage and writes are silently dropped — the classic "
            "off-by-a-tile numerical corruption."
        ),
        hint=(
            "Clip the block to a divisor of the dimension "
            "(ops/flash.py:pick_block is the house pattern) or pad "
            "the operand explicitly and mask in-kernel."
        ),
    ),
    Rule(
        id="GL502",
        name="sub-fp32-accumulator",
        summary=(
            "A pallas_call scratch accumulator allocated in a "
            "sub-fp32 float dtype (bf16/fp16) and fed by a "
            "multiply-accumulate in the kernel body. Every kernel in "
            "ops/ documents the fp32-accumulation invariant: bf16 "
            "accumulation loses ~8 mantissa bits per reduction "
            "step — at M=16k rows that is the whole gradient signal."
        ),
        hint=(
            "Allocate accumulator scratch as jnp.float32 and cast "
            "once on the final store (pltpu.VMEM(shape, jnp.float32) "
            "— see ops/fused_ffn.py's dW accumulators)."
        ),
    ),
    Rule(
        id="GL503",
        name="pallas-vmem-budget",
        summary=(
            "Estimated VMEM footprint of a pallas_call's statically-"
            "known block shapes x dtypes (in/out blocks + scratch) "
            "exceeds the budget (default 16 MiB, --vmem-budget). The "
            "estimate is a lower bound on live VMEM per program "
            "instance; Mosaic double-buffers inputs on top of it. "
            "Warn-level: an estimate gates nothing, but a kernel over "
            "budget will fail to compile on hardware long after CPU "
            "interpret-mode tests pass."
        ),
        hint=(
            "Shrink block_m/block_k (stream through a grid axis "
            "instead of holding the operand resident), or raise "
            "--vmem-budget if the target chip really has more."
        ),
        severity="warning",
    ),
    Rule(
        id="GL504",
        name="impure-kernel",
        summary=(
            "An impure call (time/random/np.random/print/logging/IO) "
            "inside a Pallas kernel body or BlockSpec index_map, or a "
            "kernel/index_map closing over a traced value from the "
            "enclosing scope. Kernel bodies lower to Mosaic — host "
            "effects are trace-time-only at best; a closed-over "
            "tracer is invisible to the grid machinery and either "
            "fails to lower or constant-folds one trace's value into "
            "every grid step."
        ),
        hint=(
            "Pass values into the kernel as refs (inputs) or "
            "functools.partial static args; index_maps must be pure "
            "functions of the grid indices."
        ),
    ),
    Rule(
        id="GL601",
        name="lock-order-inversion",
        summary=(
            "Two locks are acquired in opposite orders on different "
            "code paths (A held while taking B, and B held while "
            "taking A — directly or through method calls the engine "
            "can resolve). Two threads interleaving those paths "
            "deadlock; under load this is a when, not an if."
        ),
        hint=(
            "Pick one global order (document it on the class) and "
            "acquire in that order everywhere; or collapse to one "
            "lock; or drop the inner acquisition by snapshotting "
            "under the outer lock and working lock-free."
        ),
    ),
    Rule(
        id="GL602",
        name="blocking-call-under-lock",
        summary=(
            "A blocking call (thread .join(), time.sleep, socket/"
            "HTTP/subprocess I/O, queue .get() without timeout, "
            "Event.wait(), Condition.wait on a DIFFERENT lock) while "
            "holding a lock. Every other thread needing that lock "
            "stalls for the full blocking duration — the /health "
            "probe, the scheduler, the engine loop."
        ),
        hint=(
            "Snapshot state under the lock, release, then block; or "
            "use a timeout and re-check; Condition.wait on the held "
            "condition itself is the correct idiom and is exempt."
        ),
    ),
)

RULES_BY_ID: Dict[str, Rule] = {r.id: r for r in RULES}
RULES_BY_NAME: Dict[str, Rule] = {r.name: r for r in RULES}


def resolve_rule_token(token: str) -> str:
    """Map a suppression/CLI token (id or name, any case) to a rule id;
    returns the token unchanged when unknown (unknown ids simply never
    match a finding — a stale suppression must not crash the lint)."""
    t = token.strip()
    if t.upper() in RULES_BY_ID:
        return t.upper()
    if t.lower() in RULES_BY_NAME:
        return RULES_BY_NAME[t.lower()].id
    return t
