"""Runtime sanitizers: the dynamic half of graftlint.

The AST rules (analysis/lint.py) catch hazards the source spells out;
these sentinels catch the ones only the runtime can see — an XLA
recompilation triggered by a shape that slipped through, a blocking
device->host transfer inside a timed window. Both are context managers
designed to wrap exactly the region whose invariant the ROADMAP states:

- :class:`RecompileSentinel` pins "this window compiles at most N
  programs": the engine decode step compiles exactly once across any
  mix of requests, ``dp_step`` compiles once across M steps, a bench's
  measured window compiles zero. Counting rides jax.monitoring's
  ``/jax/core/compile/backend_compile_duration`` event — one event per
  real backend compilation, none on cache hits — so the sentinel sees
  every compile in the process, whichever thread triggered it.
- :class:`HostSyncSentinel` turns ``jax.transfer_guard_device_to_host``
  into a scoped assertion: any blocking device->host transfer inside
  the window raises (mode="disallow") or is logged by the runtime
  (mode="log"). Explicit ``jax.device_get`` calls are intercepted at
  the Python layer too, because some backends (CPU) service them
  without tripping the C++ guard. Sanctioned syncs (the log-boundary
  fetch) go through :meth:`HostSyncSentinel.allow`.

Violations are reported through the obs/ registry when one is passed
(``analysis_recompile_violations_total`` /
``analysis_host_sync_violations_total`` counters and the
``analysis_compiles_in_window`` gauge), so a fleet scrape shows
sanitizer trips next to the latency histograms they explain.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax

# one process-wide listener, installed on first use and never removed
# (jax.monitoring has no single-listener deregistration; the counter is
# a few adds per compile, nothing at steady state)
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_lock = threading.Lock()
_compiles = 0
_listener_installed = False


def _on_event(event: str, duration: float, **_kw) -> None:
    global _compiles
    if event == _COMPILE_EVENT:
        with _lock:
            _compiles += 1


def _ensure_listener() -> None:
    global _listener_installed
    with _lock:
        if _listener_installed:
            return
        # register BEFORE publishing the flag (and under the lock): a
        # flag set early would let a concurrent sentinel window open
        # against a listener that is not live yet and silently count
        # zero — the exact failure this tool exists to catch. A
        # registration error leaves the flag unset so the next caller
        # retries instead of counting nothing forever. (_on_event
        # cannot deadlock here: registration never fires events.)
        jax.monitoring.register_event_duration_secs_listener(_on_event)
        _listener_installed = True


def compile_count() -> int:
    """Process-wide backend compilations observed since the listener
    was installed. Deltas between two reads bound a window's compiles;
    :class:`RecompileSentinel` packages exactly that."""
    _ensure_listener()
    with _lock:
        return _compiles


class RecompileBudgetError(AssertionError):
    """A sentinel window compiled more XLA programs than its budget.
    An AssertionError on purpose: benches and tests treat it as a hard
    failure, never a warning to scroll past."""


class RecompileSentinel:
    """``with RecompileSentinel(budget=0, name="decode"):`` — assert at
    exit that the window triggered at most ``budget`` backend
    compilations. ``budget=None`` disables the assertion (count-only
    mode; read :attr:`count`). The check is skipped when the body
    raised — the original error is always the more useful one."""

    def __init__(self, budget: Optional[int] = 0, name: str = "window",
                 registry=None) -> None:
        self.budget = budget
        self.name = name
        self.count = 0
        self._registry = registry
        self._start = 0

    def __enter__(self) -> "RecompileSentinel":
        _ensure_listener()
        self._start = compile_count()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.count = compile_count() - self._start
        if self._registry is not None:
            self._registry.gauge(
                "analysis_compiles_in_window",
                "XLA compilations counted inside the most recent "
                "RecompileSentinel window.", labelnames=("window",),
            ).set(self.count, window=self.name)
        if exc_type is not None or self.budget is None:
            return
        if self.count > self.budget:
            if self._registry is not None:
                self._registry.counter(
                    "analysis_recompile_violations_total",
                    "RecompileSentinel windows that exceeded their "
                    "compile budget.", labelnames=("window",),
                ).inc(window=self.name)
            raise RecompileBudgetError(
                f"recompile sentinel '{self.name}': {self.count} XLA "
                f"compilation(s) inside a window budgeted for "
                f"{self.budget}. Something in this window retraces — "
                "check for shape-varying inputs, python-value statics, "
                "or a cold cache (warm up before entering the sentinel)."
            )


class HostSyncError(RuntimeError):
    """A blocking device->host transfer happened inside a
    HostSyncSentinel window that disallows them."""


class HostSyncSentinel:
    """Scoped no-host-sync assertion over the timed window.

    ``mode="disallow"`` (default) makes any blocking device->host
    transfer raise; ``mode="log"`` lets the runtime report without
    failing. The C++ transfer guard does not see every path on every
    backend (CPU services ``jax.device_get`` / ``np.asarray`` from
    host-shared buffers), so the sentinel ALSO patches
    ``jax.device_get`` for the window — between the two, ``.item()``,
    implicit ``bool()``, ``np.asarray`` and explicit ``device_get``
    are all caught on TPU, and the explicit paths everywhere.

    Sanctioned syncs nest an :meth:`allow` window::

        with HostSyncSentinel(registry=reg) as guard:
            run_steps()
            with guard.allow():     # the deliberate log-boundary fetch
                loss = float(jax.device_get(metrics["loss"]))

    Patching is process-global for the window's duration — wrap
    single-driver regions (a bench's measured loop, one engine step),
    not code concurrent with other jax drivers.
    """

    def __init__(self, mode: str = "disallow", registry=None,
                 name: str = "window") -> None:
        if mode not in ("disallow", "log"):
            raise ValueError(f"mode must be disallow|log, got {mode!r}")
        self.mode = mode
        self.name = name
        self.violations = 0
        self._registry = registry
        self._guard_ctx = None
        self._orig_device_get = None
        self._allow_depth = 0

    # -- plumbing ------------------------------------------------------

    def _record(self) -> None:
        self.violations += 1
        if self._registry is not None:
            self._registry.counter(
                "analysis_host_sync_violations_total",
                "Blocking device->host transfers flagged inside "
                "HostSyncSentinel windows.", labelnames=("window",),
            ).inc(window=self.name)

    def __enter__(self) -> "HostSyncSentinel":
        self._guard_ctx = jax.transfer_guard_device_to_host(self.mode)
        self._guard_ctx.__enter__()
        self._orig_device_get = jax.device_get
        sentinel = self

        def guarded_device_get(x):
            if sentinel._allow_depth == 0:
                sentinel._record()
                if sentinel.mode == "disallow":
                    raise HostSyncError(
                        f"host-sync sentinel '{sentinel.name}': "
                        "jax.device_get() inside a no-sync window. "
                        "Move the fetch outside the timed region or "
                        "wrap it in sentinel.allow()."
                    )
            return sentinel._orig_device_get(x)

        jax.device_get = guarded_device_get
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        jax.device_get = self._orig_device_get
        self._guard_ctx.__exit__(exc_type, exc, tb)
        if exc_type is not None and issubclass(exc_type, Exception):
            # the C++ guard raises its own error type; count it so the
            # registry sees guard trips, not just device_get ones
            if "transfer" in str(exc).lower() and exc_type is not HostSyncError:
                self._record()

    def allow(self):
        """Context manager sanctioning syncs inside the window."""
        sentinel = self

        class _Allow:
            def __enter__(self_inner):
                sentinel._allow_depth += 1
                self_inner._ctx = jax.transfer_guard_device_to_host("allow")
                self_inner._ctx.__enter__()
                return self_inner

            def __exit__(self_inner, *exc):
                self_inner._ctx.__exit__(*exc)
                sentinel._allow_depth -= 1

        return _Allow()
