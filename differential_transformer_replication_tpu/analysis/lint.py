"""graftlint's AST engine: jit-region discovery + rule dispatch.

Pure stdlib (ast + tokenize) — linting the package must not import jax
(or the package itself), so the CI gate runs in milliseconds on a
CPU-only box and can lint code that would fail to import.

How a file is analyzed:

1. **Parse + suppressions.** Each module is parsed once; ``# graftlint:``
   comment directives are collected per line (see analysis/rules.py for
   the syntax).
2. **Function graph.** Every ``def``/``lambda`` becomes a node with its
   lexical scope chain (for name resolution) and outgoing calls. Import
   statements build an alias map so ``from x import f; f()`` and
   ``import x as m; m.f()`` resolve to cross-module edges.
3. **Jit roots.** A function is a *tracing root* when it is decorated
   with (or passed to) a JAX tracing transform — ``jit``/``pjit``/
   ``pmap``/``vmap``/``grad``/``value_and_grad``/``checkpoint``/
   ``shard_map``/``lax.scan``/``cond``/``while_loop``/``fori_loop``/
   ``switch`` — including ``partial(jax.jit, ...)`` decorator forms.
   The maker idiom is followed one level: ``jax.jit(make_step(cfg))``
   marks the local functions ``make_step`` *returns* as roots.
4. **Reachability.** BFS over resolved call edges from the roots; every
   reached function is a *jit region* — its body is (part of) a traced
   program, so the GL1xx rules apply to it.
5. **Taint.** Within a jit region, values produced by ``jnp.*`` /
   ``jax.lax.*`` / ``jax.random.*`` / ``jax.nn.*`` calls (and
   anything derived from them through arithmetic, comparisons,
   subscripts and non-static attributes) are *traced*; ``.shape`` /
   ``.dtype`` / ``.ndim`` / ``len()`` strip taint (static under jit).
   The function's own parameters are *weak* taint seeds — they are the
   primary traced values of a jit region, so ``if x > 0`` / ``float(x)``
   on a bare parameter fires — but an attribute read on a bare
   parameter stays static, so static-config branches
   (``if cfg.dropout > 0``) stay clean. Parameters named by a constant
   ``static_argnums``/``static_argnames`` on the jit decorator or call
   site are not seeded at all.
6. **Interprocedural edges** (the machinery under the GL4xx/5xx/6xx
   families). Beyond direct calls, the graph follows: *maker
   variables* (``step = make_step_fn(cfg)`` then ``step(...)`` calls
   the maker's returned local defs); *function-valued parameters*
   (``make_step_fn(cfg, loss_sync=lambda l: ...)`` — a call to
   ``loss_sync`` anywhere inside the maker's scope chain resolves to
   the lambda bound at each call site); ``<fn>.defvjp(fwd, bwd)``
   (the VJP pair executes wherever the primal does); lambdas passed
   as call arguments; and functions whose parameter is handed to a
   tracing transform inside their body (``utils/compat.shard_map``'s
   ``f`` — so every wrapped body is discovered through the wrapper).
7. **Axis environments.** ``shard_map``/``pmap`` bodies *bind* mesh
   axis names (``pmap`` binds its literal ``axis_name``; ``shard_map``
   binds the wildcard ``*`` — the mesh's axes are runtime values).
   The environment propagates along the edge graph; a named-axis
   collective in a function no binder reaches is GL401. Branch arms
   of ``lax.cond``/``switch``/``while_loop`` propagate the same way
   for GL402, and ``pallas_call`` kernels / BlockSpec index_maps form
   *kernel regions* for the GL5xx checks (impure calls in a kernel
   report GL504, not GL103).
8. **Lock-order graph** (GL6xx). Per class owning a ``threading``
   lock, acquisitions are ``with self.<lock>`` / ``.acquire()``;
   while a lock is held, a directed edge is drawn to every lock
   acquired inside the block — directly, through same-class method
   calls, or through methods of attributes whose class the engine can
   resolve (``self.x = SomeClass(...)`` in ``__init__``). A cycle is
   GL601; blocking calls under a held lock are GL602.

The engine deliberately under-approximates (no interprocedural taint,
no aliasing): a finding means "this exact expression does the hazardous
thing here", which keeps the clean-tree gate (tests/test_lint_clean.py)
meaningful — suppressions mark the few deliberate exceptions instead of
papering over noise.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from differential_transformer_replication_tpu.analysis.rules import (
    RULES_BY_ID,
    resolve_rule_token,
)

# -- suppressions -------------------------------------------------------

_DIRECTIVE_RE = re.compile(r"#\s*graftlint:\s*([^#]*)")

# tracing transforms: a function passed to (or decorated by) one of
# these is traced — its body becomes part of a compiled program
_TRACING_TRANSFORMS = frozenset({
    "jit", "pjit", "pmap", "vmap", "xmap", "grad", "value_and_grad",
    "checkpoint", "remat", "custom_vjp", "custom_jvp", "scan", "cond",
    "while_loop", "fori_loop", "switch", "associative_scan",
    "shard_map", "named_call", "eval_shape",
})

# dotted prefixes whose call results are traced arrays inside a jit
# region (the taint seeds)
_ARRAY_NAMESPACES = (
    "jnp.", "jax.numpy.", "jax.lax.", "lax.", "jax.nn.", "jax.random.",
    "jax.scipy.", "jax.tree_util.tree_map", "jax.vmap", "jax.ops.",
)

# attribute reads that yield static (trace-time-concrete) metadata
_STATIC_ATTRS = frozenset({"shape", "dtype", "ndim", "size", "sharding"})

# impure dotted-name prefixes for GL103 (checked against the RESOLVED
# dotted name, so `from jax import random` does not read as stdlib
# random)
_IMPURE_PREFIXES = (
    "time.", "np.random.", "numpy.random.", "logging.", "os.environ",
    "os.getenv", "sys.stdout", "sys.stderr",
)
_IMPURE_BARE = frozenset({"print", "open", "input"})

_DONATE_NAME_RE = re.compile(r"(step|decode|prefill|update)", re.I)
_DONATE_EXEMPT_RE = re.compile(r"eval", re.I)

_STEP_CALL_RE = re.compile(r"(^|_)step$")

_LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition", "Semaphore",
                             "BoundedSemaphore"})
_EVENT_FACTORIES = frozenset({"Event"})
_COND_FACTORIES = frozenset({"Condition"})
_QUEUE_FACTORIES = frozenset({"Queue", "SimpleQueue", "LifoQueue",
                              "PriorityQueue"})

# GL4xx: named-axis collectives (communicate across shards) and
# axis-environment queries (need a binding, but never deadlock)
_COLLECTIVE_OPS = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "ppermute",
    "pshuffle", "all_to_all", "psum_scatter",
})
_AXIS_QUERY_OPS = frozenset({"axis_index", "axis_size"})

# transforms that BIND mesh axis names for their body
_BINDING_TRANSFORMS = frozenset({"shard_map", "pmap", "xmap"})
# transforms whose function args run under a traced predicate (GL402)
_BRANCH_TRANSFORMS = frozenset({"cond", "switch", "while_loop"})

# GL503: dtype byte widths the estimator understands
_DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8, "complex64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1, "bool_": 1,
    "float8_e4m3fn": 1, "float8_e5m2": 1,
}
_SUB_FP32_FLOATS = frozenset({"bfloat16", "float16"})

# GL602: dotted-call prefixes that block the calling thread
_BLOCKING_PREFIXES = (
    "time.sleep", "urllib.request.", "http.client.", "socket.",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen", "requests.",
)

DEFAULT_VMEM_BUDGET_MIB = 16.0


@dataclass
class Finding:
    path: str
    line: int
    rule: str
    message: str
    hint: str
    suppressed: bool = False
    severity: str = "error"

    @property
    def name(self) -> str:
        r = RULES_BY_ID.get(self.rule)
        return r.name if r else self.rule

    def as_dict(self) -> dict:
        return {
            "path": self.path, "line": self.line, "rule": self.rule,
            "name": self.name, "severity": self.severity,
            "message": self.message, "hint": self.hint,
            "suppressed": self.suppressed,
        }

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else (
            " (warning)" if self.severity == "warning" else ""
        )
        return (f"{self.path}:{self.line}: {self.rule} [{self.name}]"
                f"{tag}: {self.message}\n    hint: {self.hint}")


class _Suppressions:
    """Per-line and per-file rule suppression, parsed from comments."""

    def __init__(self, source: str) -> None:
        self.by_line: Dict[int, Set[str]] = {}
        self.file_wide: Set[str] = set()
        self.file_all = False
        try:
            toks = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _DIRECTIVE_RE.search(tok.string)
                if not m:
                    continue
                self._apply(m.group(1).strip(), tok.start[0])
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass  # a torn file: lint what parsed, skip its comments

    def _apply(self, body: str, line: int) -> None:
        for clause in body.split(";"):
            # a trailing parenthetical is the documented spot for the
            # why: `# graftlint: disable=GL202 (log-boundary sync)`
            clause = clause.split("(")[0].strip()
            if not clause:
                continue
            if clause == "threadsafe" or clause.startswith("threadsafe "):
                self.by_line.setdefault(line, set()).add("GL301")
            elif clause.startswith("disable-file"):
                rest = clause[len("disable-file"):].lstrip("=").strip()
                if not rest:
                    self.file_all = True
                else:
                    for t in rest.split(","):
                        if t.strip():
                            self.file_wide.add(resolve_rule_token(t))
            elif clause.startswith("disable"):
                rest = clause[len("disable"):].lstrip("=").strip()
                ids = {resolve_rule_token(t) for t in rest.split(",") if t.strip()}
                self.by_line.setdefault(line, set()).update(ids)

    def covers(self, rule: str, lines: Sequence[int]) -> bool:
        if self.file_all or rule in self.file_wide:
            return True
        return any(rule in self.by_line.get(ln, ()) for ln in lines)


# -- per-module collection ----------------------------------------------


@dataclass
class _Func:
    module: "_Mod"
    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    parent: Optional["_Func"]
    cls: Optional[str] = None  # enclosing class name, for self.* calls
    local_defs: Dict[str, "_Func"] = field(default_factory=dict)
    is_root: bool = False
    returns_jitted_probe: bool = False
    static_params: Set[str] = field(default_factory=set)
    # interprocedural machinery (PR 11): local names holding functions
    # ("func" -> the named defs; "maker" -> a maker whose RETURNED local
    # defs the name calls through)
    var_targets: Dict[str, List[Tuple[str, "_Func"]]] = field(
        default_factory=dict
    )

    @property
    def key(self) -> Tuple[str, str]:
        return (self.module.modname, self.qualname)

    def all_params(self) -> List[str]:
        a = self.node.args  # FunctionDef and Lambda expose .args alike
        return [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]


@dataclass
class _Mod:
    path: str
    relpath: str
    modname: str
    tree: ast.Module
    source: str
    suppressions: _Suppressions
    imports: Dict[str, str] = field(default_factory=dict)  # alias -> dotted
    top_defs: Dict[str, _Func] = field(default_factory=dict)
    funcs: List[_Func] = field(default_factory=list)
    classes: Dict[str, Dict[str, _Func]] = field(default_factory=dict)


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _collect_imports(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    out[a.asname] = a.name
                else:
                    head = a.name.split(".")[0]
                    out[head] = head
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


class _FuncCollector(ast.NodeVisitor):
    """Build the function/scope tree for one module."""

    def __init__(self, mod: _Mod) -> None:
        self.mod = mod
        self.stack: List[_Func] = []
        self.class_stack: List[str] = []

    def _add(self, node, name: str) -> _Func:
        parent = self.stack[-1] if self.stack else None
        qual = f"{parent.qualname}.{name}" if parent else (
            f"{self.class_stack[-1]}.{name}" if self.class_stack else name
        )
        fn = _Func(module=self.mod, qualname=qual, node=node, parent=parent,
                   cls=self.class_stack[-1] if self.class_stack else None)
        self.mod.funcs.append(fn)
        if parent is not None:
            parent.local_defs[name] = fn
        elif self.class_stack:
            self.mod.classes.setdefault(
                self.class_stack[-1], {}
            )[name] = fn
        else:
            self.mod.top_defs[name] = fn
        return fn

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_func(self, node, name: str) -> None:
        fn = self._add(node, name)
        self.stack.append(fn)
        # only descend into the body; decorators belong to the enclosing
        # scope (handled by the root-marking pass)
        for child in node.body if not isinstance(node, ast.Lambda) else [node.body]:
            self.visit(child)
        self.stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_func(node, node.name)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_func(node, node.name)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_func(node, f"<lambda:{node.lineno}>")
    # call edges are collected by the graph builder's EdgeVisitor


def _load_module(path: str, relpath: str, modname: str) -> Optional[_Mod]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source)
    except (OSError, SyntaxError, ValueError):
        return None
    mod = _Mod(path=path, relpath=relpath, modname=modname, tree=tree,
               source=source, suppressions=_Suppressions(source))
    mod.imports = _collect_imports(tree)
    _FuncCollector(mod).visit(tree)
    return mod


# -- jit-root marking + reachability ------------------------------------


def _is_tracing_transform(dotted: Optional[str]) -> bool:
    if not dotted:
        return False
    last = dotted.split(".")[-1]
    if last not in _TRACING_TRANSFORMS:
        return False
    head = dotted.split(".")[0]
    # bare `jit`/`vmap` (from jax import jit) or jax./lax./jnp.-rooted;
    # anything else (e.g. self.scan) is not JAX
    return head in _TRACING_TRANSFORMS or head in (
        "jax", "lax", "jnp", "pjit", "functools"
    )


def _positional_params(node: ast.AST) -> List[str]:
    a = node.args
    return [p.arg for p in (a.posonlyargs + a.args)]


def _const_seq(node: ast.AST) -> List[object]:
    """Constant, or tuple/list of constants, as a Python list; []
    when any element is non-constant (a dynamic static_argnums spec
    makes NOTHING static — errs toward seeding, i.e. reporting)."""
    if isinstance(node, ast.Constant):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        if all(isinstance(e, ast.Constant) for e in node.elts):
            return [e.value for e in node.elts]
    return []


def _collect_static_params(fn: _Func, keywords: List[ast.keyword]) -> None:
    """Record params a jit call marks static via constant
    static_argnums/static_argnames — they are trace-time concrete, so
    they must not seed taint."""
    pos = _positional_params(fn.node)
    for kw in keywords:
        if kw.arg == "static_argnums":
            for i in _const_seq(kw.value):
                if isinstance(i, int) and 0 <= i < len(pos):
                    fn.static_params.add(pos[i])
        elif kw.arg == "static_argnames":
            for s in _const_seq(kw.value):
                if isinstance(s, str):
                    fn.static_params.add(s)


def _scope_lookup(fn: Optional[_Func], mod: _Mod, name: str) -> Optional[_Func]:
    cur = fn
    while cur is not None:
        if name in cur.local_defs:
            return cur.local_defs[name]
        cur = cur.parent
    return mod.top_defs.get(name)


def _mark_roots(mods: Dict[str, _Mod]) -> None:
    for mod in mods.values():
        # decorators
        for fn in mod.funcs:
            node = fn.node
            if isinstance(node, ast.Lambda):
                continue
            for dec in node.decorator_list:
                d = dec.func if isinstance(dec, ast.Call) else dec
                name = _dotted(d)
                if _is_tracing_transform(name) or _alias_transform_last(
                    mod, name
                ):
                    fn.is_root = True
                    if isinstance(dec, ast.Call):
                        _collect_static_params(fn, dec.keywords)
                elif isinstance(dec, ast.Call) and name and (
                    name.split(".")[-1] == "partial"
                ):
                    # @partial(jax.jit, ...) — first positional arg is
                    # the transform
                    if dec.args and _is_tracing_transform(_dotted(dec.args[0])):
                        fn.is_root = True
                        _collect_static_params(fn, dec.keywords)

        # call-site transforms: jax.jit(f), lax.scan(body, ...),
        # partial(jax.jit, ...)(f) is rare enough to skip
        class RootVisitor(ast.NodeVisitor):
            def __init__(self) -> None:
                self.stack: List[Optional[_Func]] = [None]

            def visit_FunctionDef(self, node):
                self._push(node)

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, node):
                self._push(node)

            def _push(self, node):
                owner = next(
                    (f for f in mod.funcs if f.node is node), None
                )
                self.stack.append(owner)
                self.generic_visit(node)
                self.stack.pop()

            def visit_Call(self, node: ast.Call):
                name = _dotted(node.func)
                if _is_tracing_transform(name) or _alias_transform_last(
                    mod, name
                ):
                    scope = self.stack[-1]
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        if isinstance(arg, ast.Lambda):
                            target = next(
                                (f for f in mod.funcs if f.node is arg),
                                None,
                            )
                            if target is not None:
                                target.is_root = True
                        elif isinstance(arg, ast.Name):
                            target = _scope_lookup(scope, mod, arg.id)
                            if target is not None:
                                target.is_root = True
                                _collect_static_params(
                                    target, node.keywords
                                )
                        elif isinstance(arg, ast.Call):
                            # jax.jit(make_step(cfg)) — the MAKER's
                            # returned local functions are the roots
                            inner = _dotted(arg.func)
                            if inner and "." not in inner:
                                maker = _scope_lookup(scope, mod, inner)
                                if maker is not None:
                                    maker.returns_jitted_probe = True
                self.generic_visit(node)

        RootVisitor().visit(mod.tree)

        # maker idiom: functions whose RESULT is jitted — their returned
        # local defs become roots
        for fn in mod.funcs:
            if not fn.returns_jitted_probe or isinstance(fn.node, ast.Lambda):
                continue
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Return) and isinstance(
                    node.value, ast.Name
                ):
                    target = fn.local_defs.get(node.value.id)
                    if target is not None:
                        target.is_root = True


def _find_module(mods: Dict[str, _Mod], name: str) -> Optional[_Mod]:
    """Exact modname match, else a unique suffix match — import
    statements name modules by their import path, which may be shorter
    than the lint-root-relative modname (fixture dirs, relative
    imports)."""
    m = mods.get(name)
    if m is not None:
        return m
    suffix = "." + name
    cands = [mm for k, mm in mods.items() if k.endswith(suffix)]
    return cands[0] if len(cands) == 1 else None


def _resolve_dotted_func(
    full: str, mods: Dict[str, _Mod], depth: int = 0
) -> Optional[_Func]:
    """``pkg.mod.f`` -> the _Func, following re-export chains (a name
    imported by ``pkg/__init__.py`` from a submodule resolves through
    that module's own import aliases)."""
    if depth > 8:
        return None
    target_mod, _, func_name = full.rpartition(".")
    target = _find_module(mods, target_mod) if target_mod else None
    if target is None:
        return None
    fn = target.top_defs.get(func_name)
    if fn is not None:
        return fn
    # re-export: `from .sub import f` in the target module
    alias = target.imports.get(func_name)
    if alias is not None and alias != full:
        return _resolve_dotted_func(alias, mods, depth + 1)
    return None


def _resolve_call(
    fn: _Func, name: str, mods: Dict[str, _Mod]
) -> Optional[_Func]:
    mod = fn.module
    if "." not in name:
        local = _scope_lookup(fn, mod, name)
        if local is not None:
            return local
        # `from pkg.mod import f; f()` — the alias points at a
        # cross-module function
        alias = mod.imports.get(name)
        if alias is not None:
            return _resolve_dotted_func(alias, mods)
        return None
    head, _, rest = name.partition(".")
    if head == "self" and fn.cls and "." not in rest:
        return mod.classes.get(fn.cls, {}).get(rest)
    dotted_head = mod.imports.get(head)
    if dotted_head is None:
        return None
    full = f"{dotted_head}.{rest}" if rest else dotted_head
    return _resolve_dotted_func(full, mods)


def _alias_transform_last(mod: _Mod, name: Optional[str]) -> Optional[str]:
    """The tracing transform's SHORT name when ``name`` — as written, or
    resolved through the module's import aliases — names one; None
    otherwise. The alias path accepts jax-rooted resolutions and this
    repo's compat re-exports (``from utils.compat import shard_map as
    _shard_map`` must still read as shard_map)."""
    if not name:
        return None
    resolved = _call_dotted_resolved(mod, name)
    for cand in (name, resolved):
        last = cand.split(".")[-1]
        if last not in _TRACING_TRANSFORMS:
            continue
        parts = cand.split(".")
        if parts[0] in _TRACING_TRANSFORMS or parts[0] in (
            "jax", "lax", "jnp", "pjit", "functools"
        ):
            return last
        if cand is not name and ("jax" in parts or "compat" in parts):
            return last
    return None


def _resolve_call_any(
    scope: Optional[_Func], mod: _Mod, name: str, mods: Dict[str, _Mod]
) -> Optional[_Func]:
    """:func:`_resolve_call` that also works at module level (no
    enclosing function)."""
    if scope is not None:
        return _resolve_call(scope, name, mods)
    if "." not in name:
        fn = mod.top_defs.get(name)
        if fn is not None:
            return fn
        alias = mod.imports.get(name)
        return _resolve_dotted_func(alias, mods) if alias else None
    head, _, rest = name.partition(".")
    dotted_head = mod.imports.get(head)
    if dotted_head is None:
        return None
    return _resolve_dotted_func(
        f"{dotted_head}.{rest}" if rest else dotted_head, mods
    )


def _returned_defs(mk: _Func, depth: int = 0) -> List[_Func]:
    """The local functions a maker returns — what a call THROUGH the
    maker's result actually runs (``step = make_step_fn(cfg)``)."""
    if depth > 4 or isinstance(mk.node, ast.Lambda):
        return []
    out: List[_Func] = []
    for node in ast.walk(mk.node):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Name):
            t = mk.local_defs.get(node.value.id)
            if t is not None:
                out.append(t)
                continue
            for kind, f in _iter_var_targets(mk, node.value.id):
                if kind == "func":
                    out.append(f)
                else:
                    out.extend(_returned_defs(f, depth + 1))
    return out


def _iter_var_targets(fn: _Func, name: str):
    """Pre-resolved ("func"|"maker", _Func) pairs a local variable may
    hold (populated by the graph builder's var pass)."""
    return list(fn.var_targets.get(name, []))


@dataclass
class _PallasSite:
    mod: _Mod
    fn: Optional[_Func]
    node: ast.Call
    kernels: List[_Func]


@dataclass
class _Pending:
    # (owner_key, param) -> functions that CALL that parameter
    param_calls: Dict[Tuple[Tuple[str, str], str], List[_Func]] = field(
        default_factory=dict
    )
    # wrapper idiom: ((owner_key, param), transform_last, axes)
    transform_params: List[
        Tuple[Tuple[Tuple[str, str], str], str, Set[str]]
    ] = field(default_factory=list)
    # every resolved direct call: (caller_or_None, mod, callee, node)
    call_sites: List[
        Tuple[Optional[_Func], _Mod, _Func, ast.Call]
    ] = field(default_factory=list)


@dataclass
class _Graph:
    by_key: Dict[Tuple[str, str], _Func]
    edges: Dict[Tuple[str, str], Set[Tuple[str, str]]]
    binder_axes: Dict[Tuple[str, str], Set[str]]
    arm_seeds: Set[Tuple[str, str]]
    kernel_seeds: List[Tuple[_Func, Optional[_Func]]]
    pallas_sites: List[_PallasSite]

    def add_edge(self, src: Optional[_Func], dst: Optional[_Func]) -> None:
        if src is None or dst is None or src is dst:
            return
        self.edges.setdefault(src.key, set()).add(dst.key)


def _build_graph(mods: Dict[str, _Mod]) -> _Graph:
    """The interprocedural edge graph: direct calls plus maker
    variables, function-valued parameter bindings, ``defvjp`` pairs,
    lambda call-arguments, transform wrapper parameters, pallas
    kernels, and BlockSpec index_maps (module docstring, step 6)."""
    g = _Graph(
        by_key={f.key: f for m in mods.values() for f in m.funcs},
        edges={}, binder_axes={}, arm_seeds=set(), kernel_seeds=[],
        pallas_sites=[],
    )
    pending = _Pending()
    lambda_funcs: Dict[int, _Func] = {}
    for m in mods.values():
        for f in m.funcs:
            if isinstance(f.node, ast.Lambda):
                lambda_funcs[id(f.node)] = f

    # -- pass 1: variable -> function candidates per scope ------------
    class VarCollector(ast.NodeVisitor):
        def __init__(self, mod: _Mod) -> None:
            self.mod = mod
            self.stack: List[Optional[_Func]] = [None]

        def _push(self, node):
            owner = next((f for f in self.mod.funcs if f.node is node), None)
            self.stack.append(owner)
            self.generic_visit(node)
            self.stack.pop()

        visit_FunctionDef = visit_AsyncFunctionDef = visit_Lambda = _push

        def visit_Assign(self, node: ast.Assign):
            self.generic_visit(node)
            if len(node.targets) != 1 or not isinstance(
                node.targets[0], ast.Name
            ):
                return
            owner = self.stack[-1]
            if owner is None:
                return  # module-level function vars: top_defs covers defs
            owner.var_targets.setdefault(
                node.targets[0].id, []
            ).extend(self._classify(node.value, owner, 0))

        def _classify(self, value, owner, depth):
            if depth > 4:
                return []
            if isinstance(value, ast.Lambda):
                f = lambda_funcs.get(id(value))
                return [("func", f)] if f is not None else []
            if isinstance(value, ast.IfExp):
                return (self._classify(value.body, owner, depth + 1)
                        + self._classify(value.orelse, owner, depth + 1))
            if isinstance(value, (ast.Name, ast.Attribute)):
                name = _dotted(value)
                if not name:
                    return []
                t = _resolve_call_any(owner, self.mod, name, mods)
                return [("func", t)] if t is not None else []
            if isinstance(value, ast.Call):
                name = _dotted(value.func)
                if name and _alias_transform_last(self.mod, name):
                    # jitted = jax.jit(f) / sharded = shard_map(raw, ...):
                    # calling the variable runs the wrapped function
                    out = []
                    for a in list(value.args) + [
                        k.value for k in value.keywords
                    ]:
                        out.extend(self._classify(a, owner, depth + 1))
                    return out
                mk = (
                    _resolve_call_any(owner, self.mod, name, mods)
                    if name else None
                )
                return [("maker", mk)] if mk is not None else []
            return []

    for m in mods.values():
        VarCollector(m).visit(m.tree)

    # -- shared expression -> functions resolver ----------------------
    def funcs_from_expr(expr, scope, mod, depth=0) -> List[_Func]:
        if expr is None or depth > 6:
            return []
        if isinstance(expr, ast.Lambda):
            f = lambda_funcs.get(id(expr))
            return [f] if f is not None else []
        if isinstance(expr, ast.IfExp):
            return (funcs_from_expr(expr.body, scope, mod, depth + 1)
                    + funcs_from_expr(expr.orelse, scope, mod, depth + 1))
        if isinstance(expr, ast.Call):
            name = _dotted(expr.func)
            if name and name.split(".")[-1] == "partial" and expr.args:
                return funcs_from_expr(expr.args[0], scope, mod, depth + 1)
            out: List[_Func] = []
            for mk in funcs_from_expr(expr.func, scope, mod, depth + 1):
                out.extend(_returned_defs(mk))
            return out
        if isinstance(expr, (ast.Name, ast.Attribute)):
            name = _dotted(expr)
            if not name:
                return []
            direct = _resolve_call_any(scope, mod, name, mods)
            if direct is not None:
                return [direct]
            if "." not in name:
                cur = scope
                while cur is not None:
                    cands = _iter_var_targets(cur, name)
                    if cands:
                        out = []
                        for kind, f in cands:
                            if kind == "func":
                                out.append(f)
                            else:
                                out.extend(_returned_defs(f))
                        return out
                    cur = cur.parent
        return []

    def param_of(owner: _Func, name: str):
        cur = owner
        while cur is not None:
            if name in cur.all_params():
                return (cur.key, name)
            cur = cur.parent
        return None

    # -- pass 2: edges, seeds, sites ----------------------------------
    class EdgeVisitor(ast.NodeVisitor):
        def __init__(self, mod: _Mod) -> None:
            self.mod = mod
            self.stack: List[Optional[_Func]] = [None]

        def _push(self, node):
            owner = next((f for f in self.mod.funcs if f.node is node), None)
            self.stack.append(owner)
            self.generic_visit(node)
            self.stack.pop()

        visit_FunctionDef = visit_AsyncFunctionDef = visit_Lambda = _push

        def _axes(self, tl: str, node: ast.Call) -> Set[str]:
            if tl == "pmap":
                for kw in node.keywords:
                    if kw.arg == "axis_name" and isinstance(
                        kw.value, ast.Constant
                    ) and isinstance(kw.value.value, str):
                        return {kw.value.value}
            return {"*"}

        def _mark(self, t: _Func, tl: str, axes: Set[str], owner) -> None:
            # overlaps _mark_roots' RootVisitor on bare Name/Lambda args
            # (that pass also owns static_argnums collection and the
            # jit(make_step(cfg)) probe); this one adds IfExp/partial/
            # var-held/list-literal resolution — the root sets UNION, so
            # a resolver fix usually belongs here, a jit-semantics fix
            # there
            g.add_edge(owner, t)
            t.is_root = True
            if tl in _BINDING_TRANSFORMS:
                g.binder_axes.setdefault(t.key, set()).update(axes)
            if tl in _BRANCH_TRANSFORMS:
                g.arm_seeds.add(t.key)

        def visit_Call(self, node: ast.Call):
            owner = self.stack[-1]
            name = _dotted(node.func)
            tl = _alias_transform_last(self.mod, name) if name else None
            if tl and tl != "partial":
                axes = self._axes(tl, node)
                argexprs = list(node.args) + [
                    k.value for k in node.keywords
                ]
                flat = []
                for a in argexprs:
                    flat.extend(
                        a.elts if isinstance(a, (ast.List, ast.Tuple))
                        else [a]
                    )
                for a in flat:
                    targets = funcs_from_expr(a, owner, self.mod)
                    if (not targets and isinstance(a, ast.Name)
                            and owner is not None):
                        pw = param_of(owner, a.id)
                        if pw is not None:
                            pending.transform_params.append((pw, tl, axes))
                        continue
                    for t in targets:
                        self._mark(t, tl, axes, owner)
            elif name and name.split(".")[-1] == "pallas_call":
                kernels = (
                    funcs_from_expr(node.args[0], owner, self.mod)
                    if node.args else []
                )
                for k in kernels:
                    g.kernel_seeds.append((k, owner))
                    g.add_edge(owner, k)
                g.pallas_sites.append(
                    _PallasSite(self.mod, owner, node, kernels)
                )
            elif name and name.split(".")[-1] == "BlockSpec":
                for a in list(node.args) + [k.value for k in node.keywords]:
                    if isinstance(a, ast.Lambda):
                        f = lambda_funcs.get(id(a))
                        if f is not None:
                            g.kernel_seeds.append((f, owner))
                            g.add_edge(owner, f)
            elif (name and "." in name
                  and name.split(".")[-1] in ("defvjp", "defjvp")):
                for b in funcs_from_expr(node.func.value, owner, self.mod):
                    for arg in node.args:
                        for t in funcs_from_expr(arg, owner, self.mod):
                            g.add_edge(b, t)
            elif name:
                callee = _resolve_call_any(owner, self.mod, name, mods)
                if callee is not None:
                    g.add_edge(owner, callee)
                    pending.call_sites.append(
                        (owner, self.mod, callee, node)
                    )
                elif "." not in name and owner is not None:
                    targets = funcs_from_expr(
                        node.func, owner, self.mod
                    )
                    if targets:
                        for t in targets:
                            g.add_edge(owner, t)
                    else:
                        pw = param_of(owner, name)
                        if pw is not None:
                            pending.param_calls.setdefault(
                                pw, []
                            ).append(owner)
            # a lambda passed as ANY call argument runs inside the
            # callee's dynamic extent; approximate with a caller edge
            for a in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(a, ast.Lambda):
                    f = lambda_funcs.get(id(a))
                    if f is not None:
                        g.add_edge(owner, f)
            self.generic_visit(node)

    for m in mods.values():
        EdgeVisitor(m).visit(m.tree)

    # -- pass 3: resolve parameter bindings ---------------------------
    def bindings_for(owner_fn: _Func, pname: str):
        params = _positional_params(owner_fn.node)
        for (scope, mod, callee, node) in pending.call_sites:
            if callee is not owner_fn:
                continue
            offset = (
                1 if params and params[0] == "self"
                and isinstance(node.func, ast.Attribute) else 0
            )
            if pname in params:
                argpos = params.index(pname) - offset
                if 0 <= argpos < len(node.args):
                    yield (scope, mod, node.args[argpos])
            for kw in node.keywords:
                if kw.arg == pname:
                    yield (scope, mod, kw.value)

    for (owner_key, pname), callers in sorted(
        pending.param_calls.items(), key=lambda kv: (kv[0][0], kv[0][1])
    ):
        owner_fn = g.by_key.get(owner_key)
        if owner_fn is None:
            continue
        for (scope, mod, expr) in bindings_for(owner_fn, pname):
            for t in funcs_from_expr(expr, scope, mod):
                for caller in callers:
                    g.add_edge(caller, t)

    for (owner_key, pname), tl, axes in pending.transform_params:
        owner_fn = g.by_key.get(owner_key)
        if owner_fn is None:
            continue
        for (scope, mod, expr) in bindings_for(owner_fn, pname):
            for t in funcs_from_expr(expr, scope, mod):
                t.is_root = True
                g.add_edge(owner_fn, t)
                if tl in _BINDING_TRANSFORMS:
                    g.binder_axes.setdefault(t.key, set()).update(axes)
                if tl in _BRANCH_TRANSFORMS:
                    g.arm_seeds.add(t.key)

    return g


def _closure(
    seeds, edges: Dict[Tuple[str, str], Set[Tuple[str, str]]],
    stop: Set[Tuple[str, str]] = frozenset(),
) -> Set[Tuple[str, str]]:
    """Reachability from ``seeds``. Nodes in ``stop`` are reached but
    not expanded — how the regular-jit closure avoids flowing THROUGH a
    pallas kernel and claiming its private helpers for GL103."""
    seen = set(seeds)
    work = [k for k in seen if k not in stop]
    while work:
        k = work.pop()
        for n in edges.get(k, ()):
            if n not in seen:
                seen.add(n)
                if n not in stop:
                    work.append(n)
    return seen


def _env_closure(
    binder_axes: Dict[Tuple[str, str], Set[str]],
    edges: Dict[Tuple[str, str], Set[Tuple[str, str]]],
) -> Dict[Tuple[str, str], Set[str]]:
    """Axis environments: seeded at binder bodies, unioned along edges
    to a fixpoint. A key's ABSENCE means "no binder reaches this
    function" — the GL401 trigger."""
    env = {k: set(v) for k, v in binder_axes.items()}
    work = list(env)
    while work:
        k = work.pop()
        for n in edges.get(k, ()):
            cur = env.setdefault(n, set())
            add = env[k] - cur
            if add:
                cur.update(add)
                work.append(n)
    return env


# -- taint + jit-region rules -------------------------------------------


def _call_dotted_resolved(mod: _Mod, name: str) -> str:
    """Rewrite the leading alias of a dotted call through the import
    map, so `np.x` in a module that did `import numpy as np` resolves
    to `numpy.x` and `random.x` after `from jax import random` resolves
    to `jax.random.x`."""
    head, dot, rest = name.partition(".")
    full_head = mod.imports.get(head)
    if full_head is None:
        return name
    return f"{full_head}{dot}{rest}" if rest else full_head


def _is_array_call(mod: _Mod, name: str) -> bool:
    resolved = _call_dotted_resolved(mod, name)
    for cand in (name, resolved):
        for ns in _ARRAY_NAMESPACES:
            if cand == ns.rstrip(".") or cand.startswith(ns):
                return True
        if cand.startswith("numpy.") and not cand.startswith("numpy.random"):
            # numpy ops on traced values error; on host constants they
            # are static — numpy calls do not SEED taint, but they also
            # do not strip it (handled by expr taint propagation)
            return False
    return False


class _Taint:
    """One function's forward-pass taint state.

    Two tiers: *strong* names (``names``) are known array values —
    results of jnp/lax/random calls and anything assigned from a
    tainted expression; *weak* names (``weak``) are the function's own
    parameters. A weak name is traced when used bare (``if x > 0``,
    ``float(x)``, ``x.sum()`` — the canonical jit-region hazards) but
    an attribute read on it stays static, so config-object parameters
    (``if cfg.dropout > 0``) do not poison the clean-tree gate."""

    def __init__(self, mod: _Mod, weak: Set[str] = frozenset()) -> None:
        self.mod = mod
        self.names: Set[str] = set()
        self.weak: Set[str] = set(weak)

    def expr(self, node: ast.AST) -> bool:
        """Does evaluating ``node`` yield a traced value?"""
        if isinstance(node, ast.Name):
            return node.id in self.names or node.id in self.weak
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name and _is_array_call(self.mod, name):
                return True
            # method call on a traced value: x.sum(), x.astype(...)
            if isinstance(node.func, ast.Attribute):
                return self.expr(node.func.value)
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            if (
                isinstance(node.value, ast.Name)
                and node.value.id in self.weak
                and node.value.id not in self.names
            ):
                return False  # cfg.foo on a parameter: static config
            return self.expr(node.value)
        if isinstance(node, ast.Subscript):
            return self.expr(node.value)
        if isinstance(node, (ast.BinOp,)):
            return self.expr(node.left) or self.expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.expr(v) for v in node.values)
        if isinstance(node, ast.Compare):
            # identity tests never boolify a tracer — `x is None` /
            # `cos is not None` are core JAX idioms on traced values
            if all(isinstance(o, (ast.Is, ast.IsNot)) for o in node.ops):
                return False
            return self.expr(node.left) or any(
                self.expr(c) for c in node.comparators
            )
        if isinstance(node, ast.IfExp):
            return self.expr(node.body) or self.expr(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.expr(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        return False

    def assign(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.names.add(target.id)
            else:
                self.names.discard(target.id)
                self.weak.discard(target.id)  # param rebound to host value
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self.assign(e, tainted)
        # attribute/subscript targets: no tracked state


def _weak_param_seeds(fn: _Func) -> Set[str]:
    """The function's parameter names, minus ``self``/``cls`` and any
    param a constant static_argnums/static_argnames made trace-time
    static — the weak taint seeds for its jit region.

    Only tracing ROOTS get seeded: a root's params are by construction
    the traced arguments of a compiled program (the canonical hazard is
    `if loss > thresh` inside a @jax.jit step), while transitively
    reached helpers routinely take host-static params (chunk sizes,
    positions, flags) that would drown the gate in false positives."""
    if not fn.is_root:
        return set()
    a = fn.node.args
    names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
    for extra in (a.vararg, a.kwarg):
        if extra is not None:
            names.append(extra.arg)
    return {
        n for n in names if n not in ("self", "cls")
    } - fn.static_params


class _JitRegionChecker(ast.NodeVisitor):
    """GL101-GL107 over one jit-region function body (nested function
    bodies are their own jit regions and are skipped here). With
    ``kernel=True`` the body is a Pallas kernel / index_map: the same
    hazards apply, but impure calls report GL504 (impure-kernel) —
    inside Mosaic lowering they are a different failure mode than a
    trace-time freeze — and parameters are refs, never weak-seeded."""

    def __init__(self, fn: _Func, enabled: Set[str],
                 emit, kernel: bool = False) -> None:
        self.fn = fn
        self.mod = fn.module
        self.enabled = enabled
        self.emit = emit
        self.kernel = kernel
        self.impure_rule = "GL504" if kernel else "GL103"
        weak = set() if kernel else _weak_param_seeds(fn)
        self.taint = _Taint(fn.module, weak=weak)
        self.raise_depth = 0
        self._body_owner = fn.node

    # -- scope boundaries ---------------------------------------------
    def visit_FunctionDef(self, node):
        if node is self._body_owner:
            self.generic_visit(node)
        # nested defs: separate jit regions, checked on their own

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        if node is self._body_owner:
            self.visit(node.body)

    # -- taint bookkeeping --------------------------------------------
    def visit_Assign(self, node: ast.Assign):
        self.generic_visit(node)
        t = self.taint.expr(node.value)
        for target in node.targets:
            self.taint.assign(target, t)

    def visit_AugAssign(self, node: ast.AugAssign):
        self.generic_visit(node)
        if self.taint.expr(node.value):
            self.taint.assign(node.target, True)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        self.generic_visit(node)
        if node.value is not None:
            self.taint.assign(node.target, self.taint.expr(node.value))

    # -- GL104: traced branch -----------------------------------------
    def _check_branch(self, test: ast.AST, kind: str) -> None:
        if "GL104" in self.enabled and self.taint.expr(test):
            self.emit(
                "GL104", test.lineno,
                f"Python `{kind}` on a traced value in jit region "
                f"`{self.fn.qualname}`",
            )

    def visit_If(self, node: ast.If):
        self._check_branch(node.test, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While):
        self._check_branch(node.test, "while")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert):
        self._check_branch(node.test, "assert")
        # the assert MESSAGE runs on static data (GL105 exemption)
        self.raise_depth += 1
        self.generic_visit(node)
        self.raise_depth -= 1

    def visit_Raise(self, node: ast.Raise):
        self.raise_depth += 1
        self.generic_visit(node)
        self.raise_depth -= 1

    # -- GL106: set iteration -----------------------------------------
    def visit_For(self, node: ast.For):
        if "GL106" in self.enabled and isinstance(
            node.iter, (ast.Set, ast.SetComp)
        ):
            self.emit(
                "GL106", node.iter.lineno,
                f"iteration over a set in jit region "
                f"`{self.fn.qualname}` — trace order is hash-dependent",
            )
        self.generic_visit(node)

    def _check_comp(self, node):
        if "GL106" in self.enabled:
            for gen in node.generators:
                if isinstance(gen.iter, (ast.Set, ast.SetComp)):
                    self.emit(
                        "GL106", gen.iter.lineno,
                        f"comprehension over a set in jit region "
                        f"`{self.fn.qualname}` — trace order is "
                        "hash-dependent",
                    )
        self.generic_visit(node)

    visit_ListComp = _check_comp
    visit_SetComp = _check_comp
    visit_DictComp = _check_comp
    visit_GeneratorExp = _check_comp

    # -- GL107: global/nonlocal ---------------------------------------
    def visit_Global(self, node: ast.Global):
        if "GL107" in self.enabled:
            self.emit(
                "GL107", node.lineno,
                f"`global {', '.join(node.names)}` in jit region "
                f"`{self.fn.qualname}`",
            )

    def visit_Nonlocal(self, node: ast.Nonlocal):
        if "GL107" in self.enabled:
            self.emit(
                "GL107", node.lineno,
                f"`nonlocal {', '.join(node.names)}` in jit region "
                f"`{self.fn.qualname}`",
            )

    # -- GL105: f-strings ---------------------------------------------
    def visit_JoinedStr(self, node: ast.JoinedStr):
        if (
            "GL105" in self.enabled
            and self.raise_depth == 0
            and any(
                isinstance(v, ast.FormattedValue) for v in node.values
            )
        ):
            self.emit(
                "GL105", node.lineno,
                f"f-string in jit region `{self.fn.qualname}` "
                "(outside raise/assert)",
            )
        self.generic_visit(node)

    # -- GL101/GL102/GL103: calls -------------------------------------
    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        name = _dotted(node.func)

        # attribute-form host syncs fire regardless of taint: these
        # methods have no legitimate trace-time use on array-like values
        if isinstance(node.func, ast.Attribute) and "GL101" in self.enabled:
            if node.func.attr in ("item", "tolist", "block_until_ready"):
                self.emit(
                    "GL101", node.lineno,
                    f".{node.func.attr}() in jit region "
                    f"`{self.fn.qualname}`",
                )
                return

        if not name:
            return
        resolved = _call_dotted_resolved(self.mod, name)

        if "GL101" in self.enabled:
            if resolved.endswith("jax.device_get") or name == "jax.device_get":
                self.emit(
                    "GL101", node.lineno,
                    f"jax.device_get() in jit region `{self.fn.qualname}`",
                )
                return
            if resolved.split(".")[0] in ("numpy",) and resolved.split(".")[-1] in (
                "asarray", "array"
            ):
                if any(self.taint.expr(a) for a in node.args):
                    self.emit(
                        "GL101", node.lineno,
                        f"{name}() on a traced value in jit region "
                        f"`{self.fn.qualname}`",
                    )
                    return

        if "GL102" in self.enabled and name in ("float", "int", "bool",
                                                "complex"):
            if node.args and self.taint.expr(node.args[0]):
                self.emit(
                    "GL102", node.lineno,
                    f"{name}() on a traced value in jit region "
                    f"`{self.fn.qualname}`",
                )
                return

        if "GL105" in self.enabled and name == "str" and self.raise_depth == 0:
            if node.args and self.taint.expr(node.args[0]):
                self.emit(
                    "GL105", node.lineno,
                    f"str() of a traced value in jit region "
                    f"`{self.fn.qualname}`",
                )
                return

        if self.impure_rule in self.enabled:
            where = (
                "Pallas kernel" if self.kernel else "jit region"
            )
            if name in _IMPURE_BARE and name not in self.mod.top_defs:
                self.emit(
                    self.impure_rule, node.lineno,
                    f"impure call {name}() in {where} "
                    f"`{self.fn.qualname}`",
                )
                return
            for cand in {name, resolved}:
                if any(cand.startswith(p) for p in _IMPURE_PREFIXES):
                    self.emit(
                        self.impure_rule, node.lineno,
                        f"impure call {name}() in {where} "
                        f"`{self.fn.qualname}`",
                    )
                    return
                # stdlib `random.` — only when `random` is not an alias
                # for jax.random
                if cand.startswith("random.") and not resolved.startswith(
                    "jax.random"
                ):
                    self.emit(
                        self.impure_rule, node.lineno,
                        f"host RNG call {name}() in {where} "
                        f"`{self.fn.qualname}`",
                    )
                    return


# -- GL201: donation on step-like jit entry points ----------------------


class _DonateChecker(ast.NodeVisitor):
    def __init__(self, mod: _Mod, enabled: Set[str], emit) -> None:
        self.mod = mod
        self.enabled = enabled
        self.emit = emit

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        if "GL201" not in self.enabled:
            return
        name = _dotted(node.func)
        if not name or name.split(".")[-1] not in ("jit", "pjit"):
            return
        if name.split(".")[0] not in ("jax", "jit", "pjit"):
            return
        if not node.args:
            return
        target = node.args[0]
        tname = None
        if isinstance(target, ast.Name):
            tname = target.id
        elif isinstance(target, ast.Call):
            tname = _dotted(target.func)
        elif isinstance(target, ast.Attribute):
            tname = _dotted(target)
        if not tname:
            return  # lambdas etc.: nothing nameable to hold a policy on
        short = tname.split(".")[-1]
        if not _DONATE_NAME_RE.search(short) or _DONATE_EXEMPT_RE.search(short):
            return
        kws = {kw.arg for kw in node.keywords}
        if not ({"donate_argnums", "donate_argnames"} & kws):
            self.emit(
                "GL201", node.lineno,
                f"jax.jit({tname}, ...) — a step-like entry point "
                "jitted without donate_argnums",
            )

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self.generic_visit(node)
        if "GL201" not in self.enabled:
            return
        short = node.name
        if not _DONATE_NAME_RE.search(short) or _DONATE_EXEMPT_RE.search(short):
            return
        for dec in node.decorator_list:
            d = dec.func if isinstance(dec, ast.Call) else dec
            dname = _dotted(d) or ""
            if dname.split(".")[-1] in ("jit", "pjit") and dname.split(
                "."
            )[0] in ("jax", "jit", "pjit"):
                has_donate = isinstance(dec, ast.Call) and any(
                    kw.arg in ("donate_argnums", "donate_argnames")
                    for kw in dec.keywords
                )
                if not has_donate:
                    self.emit(
                        "GL201", dec.lineno,
                        f"@{dname} on step-like `{node.name}` without "
                        "donate_argnums",
                    )
            elif isinstance(dec, ast.Call) and dname.split(".")[-1] == "partial":
                if dec.args and (_dotted(dec.args[0]) or "").split(".")[-1] in (
                    "jit", "pjit"
                ):
                    if not any(
                        kw.arg in ("donate_argnums", "donate_argnames")
                        for kw in dec.keywords
                    ):
                        self.emit(
                            "GL201", dec.lineno,
                            f"@partial(jax.jit, ...) on step-like "
                            f"`{node.name}` without donate_argnums",
                        )


# -- GL202: host syncs inside step-dispatch loops -----------------------


class _StepLoopChecker(ast.NodeVisitor):
    """Flags blocking syncs in loops that drive a jitted step. Applies
    to HOST functions only (jit regions get the stricter GL1xx)."""

    def __init__(self, fn: _Func, enabled: Set[str], emit) -> None:
        self.fn = fn
        self.enabled = enabled
        self.emit = emit
        self.loop_depth = 0  # inside a step-dispatching loop?
        self._body_owner = fn.node

    def visit_FunctionDef(self, node):
        if node is self._body_owner:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        if node is self._body_owner:
            self.visit(node.body)

    @staticmethod
    def _loop_dispatches_step(node) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                name = _dotted(sub.func)
                if name and _STEP_CALL_RE.search(name.split(".")[-1]):
                    return True
        return False

    def _visit_loop(self, node) -> None:
        dispatches = self._loop_dispatches_step(node)
        if dispatches:
            self.loop_depth += 1
        self.generic_visit(node)
        if dispatches:
            self.loop_depth -= 1

    visit_For = _visit_loop
    visit_While = _visit_loop

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        if "GL202" not in self.enabled or self.loop_depth == 0:
            return
        name = _dotted(node.func)
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
            self.emit(
                "GL202", node.lineno,
                f".item() inside the step loop of `{self.fn.qualname}`",
            )
            return
        if not name:
            return
        if name in ("float", "int") and node.args and not isinstance(
            node.args[0], ast.Constant
        ):
            self.emit(
                "GL202", node.lineno,
                f"{name}() host sync inside the step loop of "
                f"`{self.fn.qualname}`",
            )
            return
        resolved = _call_dotted_resolved(self.fn.module, name)
        if name == "jax.device_get" or resolved == "jax.device_get":
            self.emit(
                "GL202", node.lineno,
                f"jax.device_get() inside the step loop of "
                f"`{self.fn.qualname}`",
            )


# -- GL401/GL402/GL403: sharding + collective discipline ----------------


def _axis_arg_literals(node: ast.Call, last: str) -> List[str]:
    """Literal axis names named by a collective call, [] when the axis
    expression is not statically a string (a threaded-in variable —
    bound by construction at the binding site, so unknown = no check)."""
    cand = None
    if last in _AXIS_QUERY_OPS:
        cand = node.args[0] if node.args else None
    elif len(node.args) >= 2:
        cand = node.args[1]
    for kw in node.keywords:
        # axis_name is THE name kwarg across lax collectives; `axis=`
        # on all_gather/all_to_all is the ARRAY dimension (an int) and
        # must not clobber the positional name candidate
        if kw.arg == "axis_name":
            cand = kw.value
    if cand is None:
        return []
    if isinstance(cand, ast.Constant) and isinstance(cand.value, str):
        return [cand.value]
    if isinstance(cand, (ast.Tuple, ast.List)):
        out = []
        for e in cand.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
            else:
                return []  # partially dynamic: treat as unknown
        return out
    return []


class _CollectiveChecker(ast.NodeVisitor):
    """Runs on EVERY function — host or jit region — with the axis
    environment (None = no shard_map/pmap binder reaches it) and the
    branch-arm flag computed by the interprocedural closures."""

    def __init__(self, fn: _Func, enabled: Set[str], emit,
                 env: Optional[Set[str]], in_arm: bool) -> None:
        self.fn = fn
        self.mod = fn.module
        self.enabled = enabled
        self.emit = emit
        self.env = env
        self.in_arm = in_arm
        self._body_owner = fn.node

    def visit_FunctionDef(self, node):
        if node is self._body_owner:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        if node is self._body_owner:
            self.visit(node.body)

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        name = _dotted(node.func)
        if not name:
            return
        resolved = _call_dotted_resolved(self.mod, name)
        jaxish = any(
            c.split(".")[0] in ("jax", "lax") or c.startswith("jax.")
            for c in (name, resolved)
        )
        if not jaxish:
            return
        last = name.split(".")[-1]
        if last == "device_put":
            if "GL403" in self.enabled and self.env is not None:
                self.emit(
                    "GL403", node.lineno,
                    f"jax.device_put() inside the shard_map/pmap-bound "
                    f"region `{self.fn.qualname}`",
                )
            return
        if last not in _COLLECTIVE_OPS and last not in _AXIS_QUERY_OPS:
            return
        if "GL401" in self.enabled:
            if self.env is None:
                self.emit(
                    "GL401", node.lineno,
                    f"collective {name}() in `{self.fn.qualname}`, which "
                    "no shard_map/pmap axis-binding context reaches",
                )
                return
            if "*" not in self.env:
                missing = [
                    a for a in _axis_arg_literals(node, last)
                    if a not in self.env
                ]
                if missing:
                    self.emit(
                        "GL401", node.lineno,
                        f"collective {name}() names axis "
                        f"{', '.join(repr(a) for a in missing)} not bound "
                        f"by any reachable context (bound: "
                        f"{', '.join(sorted(self.env)) or 'none'})",
                    )
                    return
        if ("GL402" in self.enabled and self.in_arm
                and last in _COLLECTIVE_OPS):
            self.emit(
                "GL402", node.lineno,
                f"collective {name}() reachable from a lax.cond/switch/"
                f"while_loop branch (`{self.fn.qualname}`) — shards "
                "taking different branches deadlock",
            )


# -- GL5xx: pallas_call sites and kernel bodies -------------------------


def _own_scope_nodes(fnnode):
    """AST nodes within ONE function's own scope — nested defs/lambdas/
    classes are separate scopes (their locals are not this scope's
    constants, and their lock acquisitions happen when the closure runs
    later, not here)."""
    stack = [fnnode]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            yield child
            stack.append(child)


def _own_scope_assigns(fnnode) -> List[ast.stmt]:
    """Assign/AugAssign statements in ONE function's own scope — a
    sibling nested helper's `BM = 100` is not the call site's BM."""
    return [
        n for n in _own_scope_nodes(fnnode)
        if isinstance(n, (ast.Assign, ast.AugAssign))
    ]


class _ConstEnv:
    """Best-effort constant folding for pallas-site checks: module-level
    single assignments plus the enclosing function chain's single
    assignments (own scopes only). Reassigned names are poisoned
    (unknown)."""

    def __init__(self, mod: _Mod, fn: Optional[_Func]) -> None:
        self.vals: Dict[str, ast.AST] = {}
        self._poison: Set[str] = set()
        self._feed(mod.tree.body)
        chain: List[_Func] = []
        cur = fn
        while cur is not None:
            chain.append(cur)
            cur = cur.parent
        for f in reversed(chain):
            if not isinstance(f.node, ast.Lambda):
                self._feed(_own_scope_assigns(f.node))

    def _feed(self, stmts) -> None:
        for st in stmts:
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                n = st.targets[0].id
                if n in self.vals or n in self._poison:
                    self._poison.add(n)
                    self.vals.pop(n, None)
                else:
                    self.vals[n] = st.value
            elif isinstance(st, ast.AugAssign) and isinstance(
                st.target, ast.Name
            ):
                self._poison.add(st.target.id)
                self.vals.pop(st.target.id, None)

    def int_of(self, node, depth: int = 0) -> Optional[int]:
        if node is None or depth > 8:
            return None
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and not isinstance(node.value, bool):
            return node.value
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            v = self.int_of(node.operand, depth + 1)
            return -v if v is not None else None
        if isinstance(node, ast.Name):
            return self.int_of(self.vals.get(node.id), depth + 1)
        if isinstance(node, ast.BinOp):
            lv = self.int_of(node.left, depth + 1)
            rv = self.int_of(node.right, depth + 1)
            if lv is None or rv is None:
                return None
            if isinstance(node.op, ast.Add):
                return lv + rv
            if isinstance(node.op, ast.Sub):
                return lv - rv
            if isinstance(node.op, ast.Mult):
                return lv * rv
            if isinstance(node.op, ast.FloorDiv):
                return lv // rv if rv else None
            if isinstance(node.op, ast.Mod):
                return lv % rv if rv else None
            return None
        if isinstance(node, ast.Subscript):
            idx = self.int_of(node.slice, depth + 1)
            seq = node.value
            if isinstance(seq, ast.Name):
                seq = self.vals.get(seq.id)
            if isinstance(seq, (ast.Tuple, ast.List)) and idx is not None \
                    and 0 <= idx < len(seq.elts):
                return self.int_of(seq.elts[idx], depth + 1)
        return None

    def dims_of(self, node) -> Optional[List[Optional[int]]]:
        if isinstance(node, ast.Name):
            node = self.vals.get(node.id)
        if isinstance(node, (ast.Tuple, ast.List)):
            return [self.int_of(e) for e in node.elts]
        return None

    def list_of(self, node) -> Optional[List[ast.AST]]:
        if isinstance(node, (ast.List, ast.Tuple)):
            return list(node.elts)
        if isinstance(node, ast.Name):
            v = self.vals.get(node.id)
            if isinstance(v, (ast.List, ast.Tuple)):
                return list(v.elts)
        return None


def _dtype_last(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    name = _dotted(node)
    return name.split(".")[-1] if name else None


def _kernel_param_layouts(kfn: _Func) -> List[List[str]]:
    """Candidate positional-parameter name lists for a kernel: the
    literal signature, or — for ``*refs`` kernels — each tuple-unpack
    of the vararg found in the body (conditional unpacks yield several
    candidates; all are checked)."""
    a = kfn.node.args
    pos = [p.arg for p in (a.posonlyargs + a.args)]
    if a.vararg is None:
        return [pos]
    layouts: List[List[str]] = []
    for n in ast.walk(kfn.node):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                and isinstance(n.value, ast.Name) \
                and n.value.id == a.vararg.arg \
                and isinstance(n.targets[0], (ast.Tuple, ast.List)) \
                and all(isinstance(e, ast.Name)
                        for e in n.targets[0].elts):
            layouts.append(pos + [e.id for e in n.targets[0].elts])
    return layouts or [pos]


def _mac_store_line(kfn: _Func, name: str) -> Optional[int]:
    """Line of an accumulating store into ref ``name``:
    ``name[...] += ...`` or ``name[...] = <expr reading name[...]>``."""
    for n in ast.walk(kfn.node):
        if isinstance(n, ast.AugAssign) \
                and isinstance(n.op, (ast.Add, ast.Sub)) \
                and isinstance(n.target, ast.Subscript) \
                and isinstance(n.target.value, ast.Name) \
                and n.target.value.id == name:
            return n.lineno
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == name:
                    for sub in ast.walk(n.value):
                        if isinstance(sub, ast.Subscript) \
                                and isinstance(sub.value, ast.Name) \
                                and sub.value.id == name:
                            return n.lineno
    return None


def _check_pallas_site(site: _PallasSite, enabled: Set[str], emit,
                       vmem_budget_mib: float) -> None:
    """GL501/GL502/GL503 at one ``pallas_call`` site, from what is
    statically provable there — unknown dims/dtypes silently skip a
    check (this is a prover, not a guesser)."""
    node = site.node
    env = _ConstEnv(site.mod, site.fn)
    kws = {k.arg: k.value for k in node.keywords if k.arg}

    def as_list(x):
        if x is None:
            return []
        lst = env.list_of(x)
        return lst if lst is not None else [x]

    def sds(entry):
        if isinstance(entry, ast.Call):
            n = (_dotted(entry.func) or "").split(".")[-1]
            if n == "ShapeDtypeStruct" and entry.args:
                return env.dims_of(entry.args[0]), (
                    _dtype_last(entry.args[1])
                    if len(entry.args) > 1 else None
                )
        return None, None

    def block_dims(entry):
        if isinstance(entry, ast.Call):
            n = (_dotted(entry.func) or "").split(".")[-1]
            if n == "BlockSpec" and entry.args:
                return env.dims_of(entry.args[0])
        return None

    def scratch_info(entry):
        if isinstance(entry, ast.Call):
            n = (_dotted(entry.func) or "").split(".")[-1]
            if n in ("VMEM", "SMEM", "ANY") and entry.args:
                return env.dims_of(entry.args[0]), (
                    _dtype_last(entry.args[1])
                    if len(entry.args) > 1 else None
                )
            if n == "ShapeDtypeStruct":
                return sds(entry)
        return None, None

    shapes = as_list(kws.get("out_shape"))
    specs = as_list(kws.get("out_specs"))
    in_specs = env.list_of(kws.get("in_specs")) or []
    scratch = env.list_of(kws.get("scratch_shapes")) or []

    if "GL501" in enabled and shapes and len(shapes) == len(specs):
        for shp_e, spec_e in zip(shapes, specs):
            dims, _dt = sds(shp_e)
            block = block_dims(spec_e)
            if not dims or not block or len(dims) != len(block):
                continue
            for d, (n_, b_) in enumerate(zip(dims, block)):
                if isinstance(n_, int) and isinstance(b_, int) \
                        and b_ > 0 and n_ % b_:
                    emit(
                        "GL501", spec_e.lineno,
                        f"out_shape dim {d} = {n_} not divisible by "
                        f"BlockSpec block dim {b_} at this pallas_call "
                        "— the ragged tail tile reads/writes garbage",
                    )

    if "GL502" in enabled and scratch and site.kernels:
        sub32 = [
            (i, scratch_info(e)[1]) for i, e in enumerate(scratch)
            if scratch_info(e)[1] in _SUB_FP32_FLOATS
        ]
        for kfn in site.kernels:
            reported: Set[Tuple[int, str]] = set()
            for names in _kernel_param_layouts(kfn):
                if len(names) < len(scratch):
                    continue
                base = len(names) - len(scratch)
                for i, dt in sub32:
                    pname = names[base + i]
                    line = _mac_store_line(kfn, pname)
                    if line and (line, pname) not in reported:
                        reported.add((line, pname))
                        emit(
                            "GL502", line,
                            f"kernel `{kfn.qualname}` accumulates into "
                            f"sub-fp32 scratch `{pname}` ({dt}) — the "
                            "fp32-accumulation invariant every ops/ "
                            "kernel documents",
                        )

    if "GL503" in enabled:
        total = 0
        for e in in_specs:
            b = block_dims(e)
            if b and all(isinstance(x, int) for x in b):
                n = 1
                for x in b:
                    n *= x
                total += n * 4  # input dtypes unseen at the site
        for shp_e, spec_e in zip(shapes, specs):
            b = block_dims(spec_e)
            _dims, dt = sds(shp_e)
            if b and all(isinstance(x, int) for x in b):
                n = 1
                for x in b:
                    n *= x
                total += n * _DTYPE_BYTES.get(dt or "", 4)
        for e in scratch:
            dims, dt = scratch_info(e)
            if dims and all(isinstance(x, int) for x in dims):
                n = 1
                for x in dims:
                    n *= x
                total += n * _DTYPE_BYTES.get(dt or "", 4)
        budget = vmem_budget_mib * 1024 * 1024
        if total > budget:
            emit(
                "GL503", node.lineno,
                f"estimated VMEM footprint {total / (1024 * 1024):.1f} "
                f"MiB (statically-known blocks + scratch) exceeds the "
                f"{vmem_budget_mib:g} MiB budget",
            )


def _strong_taint_names(fn: _Func) -> Set[str]:
    """Names bound to array-op results in ``fn``'s own body (nested
    defs excluded) — what a kernel/index_map must not close over."""
    t = _Taint(fn.module)
    owner = fn.node

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            if node is owner:
                self.generic_visit(node)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            if node is owner:
                self.visit(node.body)

        def visit_Assign(self, node):
            self.generic_visit(node)
            v = t.expr(node.value)
            for tgt in node.targets:
                t.assign(tgt, v)

        def visit_AugAssign(self, node):
            self.generic_visit(node)
            if t.expr(node.value):
                t.assign(node.target, True)

        def visit_AnnAssign(self, node):
            self.generic_visit(node)
            if node.value is not None:
                t.assign(node.target, t.expr(node.value))

    V().visit(fn.node)
    return set(t.names)


def _free_loads(fn: _Func) -> Dict[str, int]:
    """Free variables of a function: names LOADED in its body that are
    neither parameters nor bound anywhere inside it."""
    bound: Set[str] = set()
    for n in ast.walk(fn.node):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            aa = n.args
            for p in aa.posonlyargs + aa.args + aa.kwonlyargs:
                bound.add(p.arg)
            for extra in (aa.vararg, aa.kwarg):
                if extra is not None:
                    bound.add(extra.arg)
            if not isinstance(n, ast.Lambda):
                bound.add(n.name)
        elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            bound.add(n.id)
    loads: Dict[str, int] = {}
    for n in ast.walk(fn.node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                and n.id not in bound:
            loads.setdefault(n.id, n.lineno)
    return loads


def _check_kernel_closures(kfn: _Func, enclosing: Optional[_Func],
                           enabled: Set[str], emit) -> None:
    """GL504's closure half: a kernel body or index_map referencing a
    traced value from the enclosing scope."""
    if "GL504" not in enabled or enclosing is None:
        return
    tainted = _strong_taint_names(enclosing)
    if not tainted:
        return
    kind = "index_map" if isinstance(kfn.node, ast.Lambda) else "kernel"
    for name, line in sorted(_free_loads(kfn).items()):
        if name in tainted:
            emit(
                "GL504", line,
                f"{kind} `{kfn.qualname}` closes over traced value "
                f"`{name}` from `{enclosing.qualname}` — pass it in as "
                "a ref or a partial-bound static",
            )


# -- GL601/GL602: lock-order graph + blocking-under-lock ----------------


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


@dataclass
class _ClassInfo:
    mod: _Mod
    node: ast.ClassDef
    key: Tuple[str, str]  # (modname, ClassName)
    locks: Set[str] = field(default_factory=set)
    conds: Set[str] = field(default_factory=set)
    events: Set[str] = field(default_factory=set)
    queues: Set[str] = field(default_factory=set)
    attr_types: Dict[str, object] = field(default_factory=dict)
    methods: Dict[str, ast.AST] = field(default_factory=dict)


class _ConcurrencyChecker:
    """GL601/GL602 over every lock-owning class in the scanned tree.
    serving/ and tools/fleet.py are the motivating surfaces, but an
    inversion in train/ or obs/ deadlocks just the same, so the
    analysis is not directory-scoped (unlike GL301, whose shared-state
    heuristic is tuned to the serving threading model).

    The lock-order graph: one node per (class, lock attribute); while
    lock A is lexically held (``with self.A`` / ``self.A.acquire()``),
    an edge A→B is drawn for every lock B acquired inside — directly,
    through same-class method calls (transitive), or through methods
    of attributes whose class ``__init__`` makes resolvable
    (``self.x = SomeClass(...)``). A cycle means two threads can
    interleave the two paths and deadlock (GL601)."""

    def __init__(self, mods: Dict[str, _Mod], enabled: Set[str],
                 emit_for) -> None:
        self.mods = mods
        self.enabled = enabled
        self.emit_for = emit_for
        self.classes: Dict[Tuple[str, str], _ClassInfo] = {}
        self.edge_sites: Dict[Tuple, Tuple[_Mod, int]] = {}
        self.adj: Dict[Tuple, Set[Tuple]] = {}

    @staticmethod
    def _fmt(nodekey) -> str:
        (_mod, cls), attr = nodekey
        return f"{cls}.{attr}"

    def run(self) -> None:
        if not ({"GL601", "GL602"} & self.enabled):
            return
        for mod in self.mods.values():
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    self._collect(mod, node)
        for ci in self.classes.values():
            self._resolve_attr_types(ci)
        for ci in sorted(
            self.classes.values(),
            key=lambda c: (c.mod.relpath, c.node.lineno),
        ):
            if ci.locks:
                for meth in ci.methods.values():
                    self._walk_method(ci, meth)
        if "GL601" in self.enabled:
            for (u, v), (mod, line) in sorted(
                self.edge_sites.items(),
                key=lambda kv: (kv[1][0].relpath, kv[1][1], str(kv[0])),
            ):
                if self._reaches(v, u):
                    self.emit_for(mod)(
                        "GL601", line,
                        f"lock-order inversion: {self._fmt(v)} acquired "
                        f"while holding {self._fmt(u)}, but another path "
                        f"acquires {self._fmt(u)} while holding "
                        f"{self._fmt(v)}",
                    )

    def _collect(self, mod: _Mod, cls: ast.ClassDef) -> None:
        key = (mod.modname, cls.name)
        ci = _ClassInfo(mod=mod, node=cls, key=key)
        for n in cls.body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci.methods[n.name] = n
        for n in ast.walk(cls):
            if not isinstance(n, ast.Assign) or not isinstance(
                n.value, ast.Call
            ):
                continue
            vname = _dotted(n.value.func) or ""
            last = vname.split(".")[-1]
            for t in n.targets:
                attr = _self_attr(t)
                if attr is None:
                    continue
                if last in _LOCK_FACTORIES:
                    ci.locks.add(attr)
                    if last in _COND_FACTORIES:
                        ci.conds.add(attr)
                elif last in _EVENT_FACTORIES:
                    ci.events.add(attr)
                elif last in _QUEUE_FACTORIES:
                    ci.queues.add(attr)
                elif vname and last[:1].isupper():
                    ci.attr_types.setdefault(attr, vname)
        self.classes[key] = ci

    def _resolve_attr_types(self, ci: _ClassInfo) -> None:
        resolved: Dict[str, Tuple[str, str]] = {}
        for attr, vname in ci.attr_types.items():
            if "." not in vname and (ci.mod.modname, vname) in self.classes:
                resolved[attr] = (ci.mod.modname, vname)
                continue
            full = _call_dotted_resolved(ci.mod, vname)
            clsname = full.split(".")[-1]
            modpart = full.rsplit(".", 1)[0] if "." in full else ""
            m = _find_module(self.mods, modpart) if modpart else None
            if m is not None and (m.modname, clsname) in self.classes:
                resolved[attr] = (m.modname, clsname)
        ci.attr_types = resolved

    def _acquires(self, key, mname: str,
                  _seen: Optional[Set[Tuple]] = None) -> Set[Tuple]:
        """Locks a method acquires, transitively through resolvable
        calls. No memoization: a cache keyed on (class, method) gets
        permanently poisoned by cycle-guard placeholders, making GL601
        order-dependent on unrelated methods — the per-query `_seen`
        set bounds recursion instead, and the class method graphs here
        are small enough that recomputation is free."""
        if _seen is None:
            _seen = set()
        memo = (key, mname)
        if memo in _seen:
            return set()
        _seen.add(memo)
        ci = self.classes.get(key)
        out: Set[Tuple] = set()
        if ci is None or mname not in ci.methods:
            return out
        # own scope only: a callback DEFINED here acquires its locks
        # when it runs later, outside this method's lock context —
        # counting it would invent inversions (_walk_method skips
        # nested defs for the same reason)
        for n in _own_scope_nodes(ci.methods[mname]):
            if isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    a = _self_attr(item.context_expr)
                    if a in ci.locks:
                        out.add((key, a))
            elif isinstance(n, ast.Call):
                nm = _dotted(n.func) or ""
                parts = nm.split(".")
                if len(parts) == 3 and parts[0] == "self" \
                        and parts[2] == "acquire" and parts[1] in ci.locks:
                    out.add((key, parts[1]))
                elif len(parts) == 2 and parts[0] == "self":
                    out |= self._acquires(key, parts[1], _seen)
                elif len(parts) == 3 and parts[0] == "self" \
                        and parts[1] in ci.attr_types:
                    out |= self._acquires(
                        ci.attr_types[parts[1]], parts[2], _seen
                    )
        return out

    def _edge(self, u, v, mod: _Mod, line: int) -> None:
        if (u, v) not in self.edge_sites:
            self.edge_sites[(u, v)] = (mod, line)
        self.adj.setdefault(u, set()).add(v)

    def _reaches(self, src, dst) -> bool:
        seen = {src}
        work = [src]
        while work:
            k = work.pop()
            if k == dst:
                return True
            for n in self.adj.get(k, ()):
                if n not in seen:
                    seen.add(n)
                    work.append(n)
        return False

    def _walk_method(self, ci: _ClassInfo, meth) -> None:
        checker = self
        emit = self.emit_for(ci.mod)
        held: List[Tuple] = []

        class V(ast.NodeVisitor):
            def visit_With(self, node):
                acquired = []
                for item in node.items:
                    self.visit(item.context_expr)
                    a = _self_attr(item.context_expr)
                    if a is not None and a in ci.locks:
                        tgt = (ci.key, a)
                        for h in held:
                            if h != tgt:
                                checker._edge(h, tgt, ci.mod, node.lineno)
                        held.append(tgt)
                        acquired.append(tgt)
                for b in node.body:
                    self.visit(b)
                for _ in acquired:
                    held.pop()

            visit_AsyncWith = visit_With

            def visit_FunctionDef(self, node):
                if node is meth:
                    self.generic_visit(node)
                # nested defs run later, outside this lock scope

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, node):
                pass

            def visit_Call(self, node):
                self.generic_visit(node)
                if held:
                    checker._call_under_lock(ci, node, held, emit)

        V().visit(meth)

    def _call_under_lock(self, ci: _ClassInfo, node: ast.Call,
                         held: List[Tuple], emit) -> None:
        nm = _dotted(node.func) or ""
        resolved = _call_dotted_resolved(ci.mod, nm) if nm else ""
        parts = nm.split(".") if nm else []
        line = node.lineno
        # lock-order edges through calls
        acq: Set[Tuple] = set()
        if len(parts) == 3 and parts[0] == "self" \
                and parts[2] == "acquire" and parts[1] in ci.locks:
            acq = {(ci.key, parts[1])}
        elif len(parts) == 2 and parts[0] == "self":
            acq = self._acquires(ci.key, parts[1])
        elif len(parts) == 3 and parts[0] == "self" \
                and parts[1] in ci.attr_types:
            acq = self._acquires(ci.attr_types[parts[1]], parts[2])
        for tgt in acq:
            for h in held:
                if h != tgt:
                    self._edge(h, tgt, ci.mod, line)
        if "GL602" not in self.enabled:
            return
        held_names = ", ".join(self._fmt(h) for h in held)
        for cand in {nm, resolved}:
            if cand and any(
                cand.startswith(p) for p in _BLOCKING_PREFIXES
            ):
                emit(
                    "GL602", line,
                    f"blocking call {nm}() while holding {held_names}",
                )
                return
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join" and not node.args:
            emit(
                "GL602", line,
                f".join() while holding {held_names}",
            )
            return
        if len(parts) == 3 and parts[0] == "self":
            attr, m = parts[1], parts[2]
            kwnames = {k.arg for k in node.keywords}
            # queue.get is non-blocking with block=False / get(False)
            nonblocking = any(
                kw.arg == "block" and isinstance(kw.value, ast.Constant)
                and kw.value.value is False
                for kw in node.keywords
            ) or (
                node.args and isinstance(node.args[0], ast.Constant)
                and node.args[0].value is False
            )
            if attr in ci.queues and m == "get" \
                    and "timeout" not in kwnames and len(node.args) < 2 \
                    and not nonblocking:
                emit(
                    "GL602", line,
                    f"self.{attr}.get() without timeout while holding "
                    f"{held_names}",
                )
            elif attr in ci.events and m == "wait" \
                    and not node.args and "timeout" not in kwnames:
                emit(
                    "GL602", line,
                    f"self.{attr}.wait() while holding {held_names}",
                )
            elif attr in ci.conds and m in ("wait", "wait_for"):
                others = [h for h in held if h != (ci.key, attr)]
                if others:
                    emit(
                        "GL602", line,
                        f"self.{attr}.{m}() releases only self.{attr} — "
                        "still holding "
                        + ", ".join(self._fmt(h) for h in others),
                    )


# -- GL301: serving lock discipline -------------------------------------


class _LockDisciplineChecker:
    """Per-class: find lock attributes created in __init__, then flag
    attribute mutations outside `with self.<lock>` when the attribute
    is shared across methods."""

    def __init__(self, mod: _Mod, enabled: Set[str], emit) -> None:
        self.mod = mod
        self.enabled = enabled
        self.emit = emit

    def run(self) -> None:
        if "GL301" not in self.enabled:
            return
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.ClassDef):
                self._check_class(node)

    def _lock_attrs(self, cls: ast.ClassDef) -> Set[str]:
        locks: Set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            vname = _dotted(node.value.func) or ""
            if vname.split(".")[-1] not in _LOCK_FACTORIES:
                continue
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    locks.add(t.attr)
        return locks

    @staticmethod
    def _self_attr(node: ast.AST) -> Optional[str]:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr
        return None

    def _check_class(self, cls: ast.ClassDef) -> None:
        locks = self._lock_attrs(cls)
        if not locks:
            return
        methods = [
            n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        # which methods touch which self attributes (read or write)
        touched_by: Dict[str, Set[str]] = {}
        writes: List[Tuple[str, ast.AST, int, bool]] = []
        for meth in methods:
            guarded_lines = self._guarded_lines(meth, locks)
            for node in ast.walk(meth):
                attr = None
                is_write = False
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        a = self._self_attr(t)
                        if a:
                            attr, is_write = a, True
                            break
                elif isinstance(node, ast.AugAssign):
                    a = self._self_attr(node.target)
                    if a:
                        attr, is_write = a, True
                elif isinstance(node, ast.Attribute):
                    attr = self._self_attr(node)
                if attr is None or attr in locks:
                    continue
                touched_by.setdefault(attr, set()).add(meth.name)
                if is_write and meth.name != "__init__":
                    writes.append((
                        attr, node, node.lineno,
                        node.lineno in guarded_lines,
                    ))
        for attr, _node, line, guarded in writes:
            if guarded:
                continue
            if len(touched_by.get(attr, ())) < 2:
                continue  # single-method private state: not shared
            lock_names = " / ".join(
                f"self.{name}" for name in sorted(locks)
            )
            self.emit(
                "GL301", line,
                f"`self.{attr}` mutated outside `with {lock_names}` in "
                f"{cls.name} (attribute is shared across "
                f"{len(touched_by[attr])} methods)",
            )

    def _guarded_lines(self, meth, locks: Set[str]) -> Set[int]:
        """Line numbers lexically inside `with self.<lock>:` blocks."""
        out: Set[int] = set()
        for node in ast.walk(meth):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                a = self._self_attr(item.context_expr)
                if a in locks:
                    end = getattr(node, "end_lineno", node.lineno)
                    out.update(range(node.lineno, end + 1))
                    break
        return out


# -- driver -------------------------------------------------------------


def _iter_py_files(paths: Sequence[str]) -> List[Tuple[str, str, str]]:
    """(abspath, display_relpath, modname) for every .py under paths."""
    out = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p) and p.endswith(".py"):
            # keep ONE parent component so directory-scoped rules
            # (GL301: serving/) apply identically when a file is
            # spot-linted (`graftlint pkg/serving/server.py`) — and
            # same-basename file args stay distinguishable
            parent = os.path.basename(os.path.dirname(p))
            rel = (
                os.path.join(parent, os.path.basename(p))
                if parent else os.path.basename(p)
            )
            out.append((p, rel, rel[:-3].replace(os.sep, ".")))
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(
                d for d in dirnames
                if d != "__pycache__" and not d.startswith(".")
            )
            for f in sorted(filenames):
                if not f.endswith(".py"):
                    continue
                full = os.path.join(dirpath, f)
                rel = os.path.relpath(full, os.path.dirname(p))
                out.append((full, rel, _modname_for(os.path.dirname(p), full)))
    return out


def _modname_for(root: str, path: str) -> str:
    rel = os.path.relpath(path, root)
    mod = rel[:-3].replace(os.sep, ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


@dataclass
class LintResult:
    findings: List[Finding]
    files_scanned: int
    jit_regions: int
    parse_errors: List[str] = field(default_factory=list)

    @property
    def active(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def gating(self) -> List[Finding]:
        """Active findings that flip the exit code (warn-severity rules
        — GL503's VMEM estimate — are reported but never gate)."""
        return [f for f in self.active if f.severity == "error"]

    def as_dict(self) -> dict:
        return {
            "graftlint": 1,
            "files_scanned": self.files_scanned,
            "jit_regions": self.jit_regions,
            "parse_errors": list(self.parse_errors),
            "rules": sorted(RULES_BY_ID),
            "summary": {
                "total": len(self.findings),
                "active": len(self.active),
                "suppressed": len(self.findings) - len(self.active),
                "warnings": len(
                    [f for f in self.active if f.severity == "warning"]
                ),
            },
            "findings": [f.as_dict() for f in self.findings],
        }


_SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def to_sarif(result: LintResult) -> dict:
    """SARIF 2.1.0 document for CI annotation. Deterministic like the
    JSON report: rules sorted by id, results in finding order (already
    path/line/rule-sorted), suppressed findings carried with an
    ``inSource`` suppression instead of being dropped."""
    rules = [
        {
            "id": r.id,
            "name": r.name,
            "shortDescription": {"text": r.summary},
            "help": {"text": r.hint},
            "defaultConfiguration": {
                "level": "warning" if r.severity == "warning" else "error"
            },
        }
        for _id, r in sorted(RULES_BY_ID.items())
    ]
    results = []
    for f in result.findings:
        res = {
            "ruleId": f.rule,
            "level": "warning" if f.severity == "warning" else "error",
            "message": {"text": f"{f.message} (hint: {f.hint})"},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path.replace(os.sep, "/")
                        },
                        "region": {"startLine": f.line},
                    }
                }
            ],
        }
        if f.suppressed:
            res["suppressions"] = [{"kind": "inSource"}]
        results.append(res)
    for rel in result.parse_errors:
        results.append({
            "ruleId": "GL000",
            "level": "error",
            "message": {
                "text": "parse error — file silently exempt from every "
                        "rule"
            },
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": rel.replace(os.sep, "/")
                        },
                        "region": {"startLine": 1},
                    }
                }
            ],
        })
    return {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftlint",
                        # informationUri must be an ABSOLUTE URI per the
                        # SARIF schema; the repo doc lives in the help
                        # text instead
                        "fullDescription": {
                            "text": "JAX hazard linter — rule catalog "
                                    "and suppression syntax: ANALYSIS.md"
                        },
                        "rules": rules,
                    }
                },
                "results": results,
            }
        ],
    }


def lint_paths(
    paths: Sequence[str],
    rules: Optional[Sequence[str]] = None,
    *,
    files: Optional[Sequence[Tuple[str, str, str]]] = None,
    vmem_budget_mib: float = DEFAULT_VMEM_BUDGET_MIB,
) -> LintResult:
    """Lint every .py file under ``paths``; returns all findings
    (suppressed ones flagged, not dropped — the JSON output shows
    them so a suppression is an auditable decision, not a deletion).

    ``files`` (pre-enumerated ``_iter_py_files`` tuples) skips the
    directory walk — the CLI already walked each path for its
    empty-path guard and must not do the I/O twice.
    ``vmem_budget_mib`` parameterizes GL503's footprint estimate."""
    enabled: Set[str] = (
        {resolve_rule_token(r) for r in rules}
        if rules else set(RULES_BY_ID)
    )
    files = list(files) if files is not None else _iter_py_files(paths)
    mods: Dict[str, _Mod] = {}
    parse_errors: List[str] = []
    for full, rel, modname in files:
        m = _load_module(full, rel, modname)
        if m is not None:
            # same-basename spot-lint args must BOTH be scanned, not
            # last-writer-wins (an order-dependent silent lint gap);
            # disambiguated keys make cross-module resolution of the
            # colliding name ambiguous, which _find_module treats as
            # unresolvable — safe under-approximation
            key, i = modname, 2
            while key in mods:
                key, i = f"{modname}#{i}", i + 1
            m.modname = key
            mods[key] = m
        else:
            # an unparseable file would otherwise be SILENTLY exempt
            # from every rule — surface it (callers decide severity)
            parse_errors.append(rel)

    _mark_roots(mods)
    graph = _build_graph(mods)

    # Pallas kernel regions: each kernel/index_map plus every function
    # nested inside it (pl.when bodies execute within the kernel) plus
    # everything they call. A function reached ONLY through kernels
    # reports impure calls as GL504; one also reachable from ordinary
    # tracing roots keeps GL103.
    kernel_keys: Set[Tuple[str, str]] = set()
    kernel_enclosing: List[Tuple[_Func, Optional[_Func]]] = []
    seen_kernels: Set[Tuple[str, str]] = set()
    for (kf, enc) in graph.kernel_seeds:
        if kf.key not in seen_kernels:
            seen_kernels.add(kf.key)
            kernel_enclosing.append((kf, enc))
        kernel_keys.add(kf.key)
    for m in mods.values():
        for f in m.funcs:  # pre-order: parents precede children
            cur = f.parent
            while cur is not None:
                if cur.key in kernel_keys:
                    kernel_keys.add(f.key)
                    break
                cur = cur.parent

    kernelish = _closure(kernel_keys, graph.edges)
    root_keys = {
        f.key for m in mods.values() for f in m.funcs
        if f.is_root and f.key not in kernel_keys
    }
    # regular jit reachability STOPS at kernels: a jitted caller of a
    # pallas_call reaches the kernel, but the kernel (and helpers only
    # it calls) stay kernel regions — impure calls there are GL504,
    # not GL103, no matter where the call site sits
    regular = _closure(root_keys, graph.edges, stop=kernel_keys)
    regions = regular | kernelish
    kernel_only = kernelish - (regular - kernel_keys)
    envs = _env_closure(graph.binder_axes, graph.edges)
    arms = _closure(graph.arm_seeds, graph.edges)

    findings: List[Finding] = []

    def make_emit(mod: _Mod):
        def emit(rule: str, line: int, message: str) -> None:
            r = RULES_BY_ID[rule]
            # a suppression may sit on the reported line or anywhere in
            # the enclosing statement (multi-line calls)
            lines = _statement_lines(mod, line)
            findings.append(Finding(
                path=mod.relpath, line=line, rule=rule,
                message=message, hint=r.hint,
                suppressed=mod.suppressions.covers(rule, lines),
                severity=r.severity,
            ))
        return emit

    stmt_cache: Dict[str, List[Tuple[int, int]]] = {}

    def _statement_lines(mod: _Mod, line: int) -> List[int]:
        # keyed by ABSOLUTE path: two same-basename file args share a
        # display relpath (serving/x.py) but must not share spans, or
        # one file's suppression coverage silently applies the other's
        # statement extents
        spans = stmt_cache.get(mod.path)
        if spans is None:
            spans = []
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.stmt):
                    spans.append(
                        (node.lineno, getattr(node, "end_lineno", node.lineno))
                    )
            stmt_cache[mod.path] = spans
        best: Optional[Tuple[int, int]] = None
        for lo, hi in spans:
            if lo <= line <= hi and (
                best is None or (hi - lo) < (best[1] - best[0])
            ):
                best = (lo, hi)
        if best is None:
            return [line]
        return list(range(best[0], best[1] + 1))

    emit_by: Dict[int, object] = {}

    def emit_for(mod: _Mod):
        e = emit_by.get(id(mod))
        if e is None:
            e = make_emit(mod)
            emit_by[id(mod)] = e
        return e

    for mod in mods.values():
        emit = emit_for(mod)
        for fn in mod.funcs:
            if fn.key in regions:
                _JitRegionChecker(
                    fn, enabled, emit, kernel=fn.key in kernel_only
                ).visit(fn.node)
            else:
                _StepLoopChecker(fn, enabled, emit).visit(fn.node)
            _CollectiveChecker(
                fn, enabled, emit, envs.get(fn.key), fn.key in arms
            ).visit(fn.node)
        _DonateChecker(mod, enabled, emit).visit(mod.tree)
        # membership keyed on the lint-root-RELATIVE path (file args
        # keep one parent component, so spot-linting serving/server.py
        # still applies the rule) — never the absolute path, which
        # would drag a whole checkout under /home/serving/... into the
        # serving-only rules
        if "serving" in mod.relpath.split(os.sep):
            _LockDisciplineChecker(mod, enabled, emit).run()

    for site in graph.pallas_sites:
        _check_pallas_site(
            site, enabled, emit_for(site.mod), vmem_budget_mib
        )
    for (kf, enc) in kernel_enclosing:
        _check_kernel_closures(kf, enc, enabled, emit_for(kf.module))
    _ConcurrencyChecker(mods, enabled, emit_for).run()

    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return LintResult(
        findings=findings, files_scanned=len(mods),
        jit_regions=len(regions), parse_errors=sorted(parse_errors),
    )
