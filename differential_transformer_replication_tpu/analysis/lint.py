"""graftlint's AST engine: jit-region discovery + rule dispatch.

Pure stdlib (ast + tokenize) — linting the package must not import jax
(or the package itself), so the CI gate runs in milliseconds on a
CPU-only box and can lint code that would fail to import.

How a file is analyzed:

1. **Parse + suppressions.** Each module is parsed once; ``# graftlint:``
   comment directives are collected per line (see analysis/rules.py for
   the syntax).
2. **Function graph.** Every ``def``/``lambda`` becomes a node with its
   lexical scope chain (for name resolution) and outgoing calls. Import
   statements build an alias map so ``from x import f; f()`` and
   ``import x as m; m.f()`` resolve to cross-module edges.
3. **Jit roots.** A function is a *tracing root* when it is decorated
   with (or passed to) a JAX tracing transform — ``jit``/``pjit``/
   ``pmap``/``vmap``/``grad``/``value_and_grad``/``checkpoint``/
   ``shard_map``/``lax.scan``/``cond``/``while_loop``/``fori_loop``/
   ``switch`` — including ``partial(jax.jit, ...)`` decorator forms.
   The maker idiom is followed one level: ``jax.jit(make_step(cfg))``
   marks the local functions ``make_step`` *returns* as roots.
4. **Reachability.** BFS over resolved call edges from the roots; every
   reached function is a *jit region* — its body is (part of) a traced
   program, so the GL1xx rules apply to it.
5. **Taint.** Within a jit region, values produced by ``jnp.*`` /
   ``jax.lax.*`` / ``jax.random.*`` / ``jax.nn.*`` calls (and
   anything derived from them through arithmetic, comparisons,
   subscripts and non-static attributes) are *traced*; ``.shape`` /
   ``.dtype`` / ``.ndim`` / ``len()`` strip taint (static under jit).
   The function's own parameters are *weak* taint seeds — they are the
   primary traced values of a jit region, so ``if x > 0`` / ``float(x)``
   on a bare parameter fires — but an attribute read on a bare
   parameter stays static, so static-config branches
   (``if cfg.dropout > 0``) stay clean. Parameters named by a constant
   ``static_argnums``/``static_argnames`` on the jit decorator or call
   site are not seeded at all.

The engine deliberately under-approximates (no interprocedural taint,
no aliasing): a finding means "this exact expression does the hazardous
thing here", which keeps the clean-tree gate (tests/test_lint_clean.py)
meaningful — suppressions mark the few deliberate exceptions instead of
papering over noise.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from differential_transformer_replication_tpu.analysis.rules import (
    RULES_BY_ID,
    resolve_rule_token,
)

# -- suppressions -------------------------------------------------------

_DIRECTIVE_RE = re.compile(r"#\s*graftlint:\s*([^#]*)")

# tracing transforms: a function passed to (or decorated by) one of
# these is traced — its body becomes part of a compiled program
_TRACING_TRANSFORMS = frozenset({
    "jit", "pjit", "pmap", "vmap", "xmap", "grad", "value_and_grad",
    "checkpoint", "remat", "custom_vjp", "custom_jvp", "scan", "cond",
    "while_loop", "fori_loop", "switch", "associative_scan",
    "shard_map", "named_call", "eval_shape",
})

# dotted prefixes whose call results are traced arrays inside a jit
# region (the taint seeds)
_ARRAY_NAMESPACES = (
    "jnp.", "jax.numpy.", "jax.lax.", "lax.", "jax.nn.", "jax.random.",
    "jax.scipy.", "jax.tree_util.tree_map", "jax.vmap", "jax.ops.",
)

# attribute reads that yield static (trace-time-concrete) metadata
_STATIC_ATTRS = frozenset({"shape", "dtype", "ndim", "size", "sharding"})

# impure dotted-name prefixes for GL103 (checked against the RESOLVED
# dotted name, so `from jax import random` does not read as stdlib
# random)
_IMPURE_PREFIXES = (
    "time.", "np.random.", "numpy.random.", "logging.", "os.environ",
    "os.getenv", "sys.stdout", "sys.stderr",
)
_IMPURE_BARE = frozenset({"print", "open", "input"})

_DONATE_NAME_RE = re.compile(r"(step|decode|prefill|update)", re.I)
_DONATE_EXEMPT_RE = re.compile(r"eval", re.I)

_STEP_CALL_RE = re.compile(r"(^|_)step$")

_LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition", "Semaphore",
                             "BoundedSemaphore"})


@dataclass
class Finding:
    path: str
    line: int
    rule: str
    message: str
    hint: str
    suppressed: bool = False

    @property
    def name(self) -> str:
        r = RULES_BY_ID.get(self.rule)
        return r.name if r else self.rule

    def as_dict(self) -> dict:
        return {
            "path": self.path, "line": self.line, "rule": self.rule,
            "name": self.name, "message": self.message, "hint": self.hint,
            "suppressed": self.suppressed,
        }

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return (f"{self.path}:{self.line}: {self.rule} [{self.name}]"
                f"{tag}: {self.message}\n    hint: {self.hint}")


class _Suppressions:
    """Per-line and per-file rule suppression, parsed from comments."""

    def __init__(self, source: str) -> None:
        self.by_line: Dict[int, Set[str]] = {}
        self.file_wide: Set[str] = set()
        self.file_all = False
        try:
            toks = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _DIRECTIVE_RE.search(tok.string)
                if not m:
                    continue
                self._apply(m.group(1).strip(), tok.start[0])
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass  # a torn file: lint what parsed, skip its comments

    def _apply(self, body: str, line: int) -> None:
        for clause in body.split(";"):
            # a trailing parenthetical is the documented spot for the
            # why: `# graftlint: disable=GL202 (log-boundary sync)`
            clause = clause.split("(")[0].strip()
            if not clause:
                continue
            if clause == "threadsafe" or clause.startswith("threadsafe "):
                self.by_line.setdefault(line, set()).add("GL301")
            elif clause.startswith("disable-file"):
                rest = clause[len("disable-file"):].lstrip("=").strip()
                if not rest:
                    self.file_all = True
                else:
                    for t in rest.split(","):
                        if t.strip():
                            self.file_wide.add(resolve_rule_token(t))
            elif clause.startswith("disable"):
                rest = clause[len("disable"):].lstrip("=").strip()
                ids = {resolve_rule_token(t) for t in rest.split(",") if t.strip()}
                self.by_line.setdefault(line, set()).update(ids)

    def covers(self, rule: str, lines: Sequence[int]) -> bool:
        if self.file_all or rule in self.file_wide:
            return True
        return any(rule in self.by_line.get(ln, ()) for ln in lines)


# -- per-module collection ----------------------------------------------


@dataclass
class _Func:
    module: "_Mod"
    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    parent: Optional["_Func"]
    cls: Optional[str] = None  # enclosing class name, for self.* calls
    local_defs: Dict[str, "_Func"] = field(default_factory=dict)
    calls: List[Tuple[str, int]] = field(default_factory=list)
    is_root: bool = False
    returns_jitted_probe: bool = False
    static_params: Set[str] = field(default_factory=set)

    @property
    def key(self) -> Tuple[str, str]:
        return (self.module.modname, self.qualname)


@dataclass
class _Mod:
    path: str
    relpath: str
    modname: str
    tree: ast.Module
    source: str
    suppressions: _Suppressions
    imports: Dict[str, str] = field(default_factory=dict)  # alias -> dotted
    top_defs: Dict[str, _Func] = field(default_factory=dict)
    funcs: List[_Func] = field(default_factory=list)
    classes: Dict[str, Dict[str, _Func]] = field(default_factory=dict)


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _collect_imports(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    out[a.asname] = a.name
                else:
                    head = a.name.split(".")[0]
                    out[head] = head
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


class _FuncCollector(ast.NodeVisitor):
    """Build the function/scope tree for one module."""

    def __init__(self, mod: _Mod) -> None:
        self.mod = mod
        self.stack: List[_Func] = []
        self.class_stack: List[str] = []

    def _add(self, node, name: str) -> _Func:
        parent = self.stack[-1] if self.stack else None
        qual = f"{parent.qualname}.{name}" if parent else (
            f"{self.class_stack[-1]}.{name}" if self.class_stack else name
        )
        fn = _Func(module=self.mod, qualname=qual, node=node, parent=parent,
                   cls=self.class_stack[-1] if self.class_stack else None)
        self.mod.funcs.append(fn)
        if parent is not None:
            parent.local_defs[name] = fn
        elif self.class_stack:
            self.mod.classes.setdefault(
                self.class_stack[-1], {}
            )[name] = fn
        else:
            self.mod.top_defs[name] = fn
        return fn

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_func(self, node, name: str) -> None:
        fn = self._add(node, name)
        self.stack.append(fn)
        # only descend into the body; decorators belong to the enclosing
        # scope (handled by the root-marking pass)
        for child in node.body if not isinstance(node, ast.Lambda) else [node.body]:
            self.visit(child)
        self.stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_func(node, node.name)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_func(node, node.name)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_func(node, f"<lambda:{node.lineno}>")

    def visit_Call(self, node: ast.Call) -> None:
        if self.stack:
            name = _dotted(node.func)
            if name:
                self.stack[-1].calls.append((name, node.lineno))
        self.generic_visit(node)


def _load_module(path: str, relpath: str, modname: str) -> Optional[_Mod]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source)
    except (OSError, SyntaxError, ValueError):
        return None
    mod = _Mod(path=path, relpath=relpath, modname=modname, tree=tree,
               source=source, suppressions=_Suppressions(source))
    mod.imports = _collect_imports(tree)
    _FuncCollector(mod).visit(tree)
    return mod


# -- jit-root marking + reachability ------------------------------------


def _is_tracing_transform(dotted: Optional[str]) -> bool:
    if not dotted:
        return False
    last = dotted.split(".")[-1]
    if last not in _TRACING_TRANSFORMS:
        return False
    head = dotted.split(".")[0]
    # bare `jit`/`vmap` (from jax import jit) or jax./lax./jnp.-rooted;
    # anything else (e.g. self.scan) is not JAX
    return head in _TRACING_TRANSFORMS or head in (
        "jax", "lax", "jnp", "pjit", "functools"
    )


def _positional_params(node: ast.AST) -> List[str]:
    a = node.args
    return [p.arg for p in (a.posonlyargs + a.args)]


def _const_seq(node: ast.AST) -> List[object]:
    """Constant, or tuple/list of constants, as a Python list; []
    when any element is non-constant (a dynamic static_argnums spec
    makes NOTHING static — errs toward seeding, i.e. reporting)."""
    if isinstance(node, ast.Constant):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        if all(isinstance(e, ast.Constant) for e in node.elts):
            return [e.value for e in node.elts]
    return []


def _collect_static_params(fn: _Func, keywords: List[ast.keyword]) -> None:
    """Record params a jit call marks static via constant
    static_argnums/static_argnames — they are trace-time concrete, so
    they must not seed taint."""
    pos = _positional_params(fn.node)
    for kw in keywords:
        if kw.arg == "static_argnums":
            for i in _const_seq(kw.value):
                if isinstance(i, int) and 0 <= i < len(pos):
                    fn.static_params.add(pos[i])
        elif kw.arg == "static_argnames":
            for s in _const_seq(kw.value):
                if isinstance(s, str):
                    fn.static_params.add(s)


def _scope_lookup(fn: Optional[_Func], mod: _Mod, name: str) -> Optional[_Func]:
    cur = fn
    while cur is not None:
        if name in cur.local_defs:
            return cur.local_defs[name]
        cur = cur.parent
    return mod.top_defs.get(name)


def _mark_roots(mods: Dict[str, _Mod]) -> None:
    for mod in mods.values():
        # decorators
        for fn in mod.funcs:
            node = fn.node
            if isinstance(node, ast.Lambda):
                continue
            for dec in node.decorator_list:
                d = dec.func if isinstance(dec, ast.Call) else dec
                name = _dotted(d)
                if _is_tracing_transform(name):
                    fn.is_root = True
                    if isinstance(dec, ast.Call):
                        _collect_static_params(fn, dec.keywords)
                elif isinstance(dec, ast.Call) and name and (
                    name.split(".")[-1] == "partial"
                ):
                    # @partial(jax.jit, ...) — first positional arg is
                    # the transform
                    if dec.args and _is_tracing_transform(_dotted(dec.args[0])):
                        fn.is_root = True
                        _collect_static_params(fn, dec.keywords)

        # call-site transforms: jax.jit(f), lax.scan(body, ...),
        # partial(jax.jit, ...)(f) is rare enough to skip
        class RootVisitor(ast.NodeVisitor):
            def __init__(self) -> None:
                self.stack: List[Optional[_Func]] = [None]

            def visit_FunctionDef(self, node):
                self._push(node)

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, node):
                self._push(node)

            def _push(self, node):
                owner = next(
                    (f for f in mod.funcs if f.node is node), None
                )
                self.stack.append(owner)
                self.generic_visit(node)
                self.stack.pop()

            def visit_Call(self, node: ast.Call):
                name = _dotted(node.func)
                if _is_tracing_transform(name):
                    scope = self.stack[-1]
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        if isinstance(arg, ast.Lambda):
                            target = next(
                                (f for f in mod.funcs if f.node is arg),
                                None,
                            )
                            if target is not None:
                                target.is_root = True
                        elif isinstance(arg, ast.Name):
                            target = _scope_lookup(scope, mod, arg.id)
                            if target is not None:
                                target.is_root = True
                                _collect_static_params(
                                    target, node.keywords
                                )
                        elif isinstance(arg, ast.Call):
                            # jax.jit(make_step(cfg)) — the MAKER's
                            # returned local functions are the roots
                            inner = _dotted(arg.func)
                            if inner and "." not in inner:
                                maker = _scope_lookup(scope, mod, inner)
                                if maker is not None:
                                    maker.returns_jitted_probe = True
                self.generic_visit(node)

        RootVisitor().visit(mod.tree)

        # maker idiom: functions whose RESULT is jitted — their returned
        # local defs become roots
        for fn in mod.funcs:
            if not fn.returns_jitted_probe or isinstance(fn.node, ast.Lambda):
                continue
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Return) and isinstance(
                    node.value, ast.Name
                ):
                    target = fn.local_defs.get(node.value.id)
                    if target is not None:
                        target.is_root = True


def _find_module(mods: Dict[str, _Mod], name: str) -> Optional[_Mod]:
    """Exact modname match, else a unique suffix match — import
    statements name modules by their import path, which may be shorter
    than the lint-root-relative modname (fixture dirs, relative
    imports)."""
    m = mods.get(name)
    if m is not None:
        return m
    suffix = "." + name
    cands = [mm for k, mm in mods.items() if k.endswith(suffix)]
    return cands[0] if len(cands) == 1 else None


def _resolve_dotted_func(
    full: str, mods: Dict[str, _Mod], depth: int = 0
) -> Optional[_Func]:
    """``pkg.mod.f`` -> the _Func, following re-export chains (a name
    imported by ``pkg/__init__.py`` from a submodule resolves through
    that module's own import aliases)."""
    if depth > 8:
        return None
    target_mod, _, func_name = full.rpartition(".")
    target = _find_module(mods, target_mod) if target_mod else None
    if target is None:
        return None
    fn = target.top_defs.get(func_name)
    if fn is not None:
        return fn
    # re-export: `from .sub import f` in the target module
    alias = target.imports.get(func_name)
    if alias is not None and alias != full:
        return _resolve_dotted_func(alias, mods, depth + 1)
    return None


def _resolve_call(
    fn: _Func, name: str, mods: Dict[str, _Mod]
) -> Optional[_Func]:
    mod = fn.module
    if "." not in name:
        local = _scope_lookup(fn, mod, name)
        if local is not None:
            return local
        # `from pkg.mod import f; f()` — the alias points at a
        # cross-module function
        alias = mod.imports.get(name)
        if alias is not None:
            return _resolve_dotted_func(alias, mods)
        return None
    head, _, rest = name.partition(".")
    if head == "self" and fn.cls and "." not in rest:
        return mod.classes.get(fn.cls, {}).get(rest)
    dotted_head = mod.imports.get(head)
    if dotted_head is None:
        return None
    full = f"{dotted_head}.{rest}" if rest else dotted_head
    return _resolve_dotted_func(full, mods)


def _reachable_jit_regions(mods: Dict[str, _Mod]) -> Set[Tuple[str, str]]:
    # `from mod import f` aliases: imports map may point directly at a
    # function (pkg.mod.f) — _resolve_call handles both layouts
    work: List[_Func] = [
        f for m in mods.values() for f in m.funcs if f.is_root
    ]
    seen: Set[Tuple[str, str]] = {f.key for f in work}
    by_key = {
        f.key: f for m in mods.values() for f in m.funcs
    }
    while work:
        fn = work.pop()
        for name, _line in fn.calls:
            callee = _resolve_call(fn, name, mods)
            if callee is not None and callee.key not in seen:
                seen.add(callee.key)
                work.append(callee)
    return seen & set(by_key)


# -- taint + jit-region rules -------------------------------------------


def _call_dotted_resolved(mod: _Mod, name: str) -> str:
    """Rewrite the leading alias of a dotted call through the import
    map, so `np.x` in a module that did `import numpy as np` resolves
    to `numpy.x` and `random.x` after `from jax import random` resolves
    to `jax.random.x`."""
    head, dot, rest = name.partition(".")
    full_head = mod.imports.get(head)
    if full_head is None:
        return name
    return f"{full_head}{dot}{rest}" if rest else full_head


def _is_array_call(mod: _Mod, name: str) -> bool:
    resolved = _call_dotted_resolved(mod, name)
    for cand in (name, resolved):
        for ns in _ARRAY_NAMESPACES:
            if cand == ns.rstrip(".") or cand.startswith(ns):
                return True
        if cand.startswith("numpy.") and not cand.startswith("numpy.random"):
            # numpy ops on traced values error; on host constants they
            # are static — numpy calls do not SEED taint, but they also
            # do not strip it (handled by expr taint propagation)
            return False
    return False


class _Taint:
    """One function's forward-pass taint state.

    Two tiers: *strong* names (``names``) are known array values —
    results of jnp/lax/random calls and anything assigned from a
    tainted expression; *weak* names (``weak``) are the function's own
    parameters. A weak name is traced when used bare (``if x > 0``,
    ``float(x)``, ``x.sum()`` — the canonical jit-region hazards) but
    an attribute read on it stays static, so config-object parameters
    (``if cfg.dropout > 0``) do not poison the clean-tree gate."""

    def __init__(self, mod: _Mod, weak: Set[str] = frozenset()) -> None:
        self.mod = mod
        self.names: Set[str] = set()
        self.weak: Set[str] = set(weak)

    def expr(self, node: ast.AST) -> bool:
        """Does evaluating ``node`` yield a traced value?"""
        if isinstance(node, ast.Name):
            return node.id in self.names or node.id in self.weak
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name and _is_array_call(self.mod, name):
                return True
            # method call on a traced value: x.sum(), x.astype(...)
            if isinstance(node.func, ast.Attribute):
                return self.expr(node.func.value)
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            if (
                isinstance(node.value, ast.Name)
                and node.value.id in self.weak
                and node.value.id not in self.names
            ):
                return False  # cfg.foo on a parameter: static config
            return self.expr(node.value)
        if isinstance(node, ast.Subscript):
            return self.expr(node.value)
        if isinstance(node, (ast.BinOp,)):
            return self.expr(node.left) or self.expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.expr(v) for v in node.values)
        if isinstance(node, ast.Compare):
            # identity tests never boolify a tracer — `x is None` /
            # `cos is not None` are core JAX idioms on traced values
            if all(isinstance(o, (ast.Is, ast.IsNot)) for o in node.ops):
                return False
            return self.expr(node.left) or any(
                self.expr(c) for c in node.comparators
            )
        if isinstance(node, ast.IfExp):
            return self.expr(node.body) or self.expr(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.expr(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        return False

    def assign(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.names.add(target.id)
            else:
                self.names.discard(target.id)
                self.weak.discard(target.id)  # param rebound to host value
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self.assign(e, tainted)
        # attribute/subscript targets: no tracked state


def _weak_param_seeds(fn: _Func) -> Set[str]:
    """The function's parameter names, minus ``self``/``cls`` and any
    param a constant static_argnums/static_argnames made trace-time
    static — the weak taint seeds for its jit region.

    Only tracing ROOTS get seeded: a root's params are by construction
    the traced arguments of a compiled program (the canonical hazard is
    `if loss > thresh` inside a @jax.jit step), while transitively
    reached helpers routinely take host-static params (chunk sizes,
    positions, flags) that would drown the gate in false positives."""
    if not fn.is_root:
        return set()
    a = fn.node.args
    names = [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]
    for extra in (a.vararg, a.kwarg):
        if extra is not None:
            names.append(extra.arg)
    return {
        n for n in names if n not in ("self", "cls")
    } - fn.static_params


class _JitRegionChecker(ast.NodeVisitor):
    """GL101-GL107 over one jit-region function body (nested function
    bodies are their own jit regions and are skipped here)."""

    def __init__(self, fn: _Func, enabled: Set[str],
                 emit) -> None:
        self.fn = fn
        self.mod = fn.module
        self.enabled = enabled
        self.emit = emit
        self.taint = _Taint(fn.module, weak=_weak_param_seeds(fn))
        self.raise_depth = 0
        self._body_owner = fn.node

    # -- scope boundaries ---------------------------------------------
    def visit_FunctionDef(self, node):
        if node is self._body_owner:
            self.generic_visit(node)
        # nested defs: separate jit regions, checked on their own

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        if node is self._body_owner:
            self.visit(node.body)

    # -- taint bookkeeping --------------------------------------------
    def visit_Assign(self, node: ast.Assign):
        self.generic_visit(node)
        t = self.taint.expr(node.value)
        for target in node.targets:
            self.taint.assign(target, t)

    def visit_AugAssign(self, node: ast.AugAssign):
        self.generic_visit(node)
        if self.taint.expr(node.value):
            self.taint.assign(node.target, True)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        self.generic_visit(node)
        if node.value is not None:
            self.taint.assign(node.target, self.taint.expr(node.value))

    # -- GL104: traced branch -----------------------------------------
    def _check_branch(self, test: ast.AST, kind: str) -> None:
        if "GL104" in self.enabled and self.taint.expr(test):
            self.emit(
                "GL104", test.lineno,
                f"Python `{kind}` on a traced value in jit region "
                f"`{self.fn.qualname}`",
            )

    def visit_If(self, node: ast.If):
        self._check_branch(node.test, "if")
        self.generic_visit(node)

    def visit_While(self, node: ast.While):
        self._check_branch(node.test, "while")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert):
        self._check_branch(node.test, "assert")
        # the assert MESSAGE runs on static data (GL105 exemption)
        self.raise_depth += 1
        self.generic_visit(node)
        self.raise_depth -= 1

    def visit_Raise(self, node: ast.Raise):
        self.raise_depth += 1
        self.generic_visit(node)
        self.raise_depth -= 1

    # -- GL106: set iteration -----------------------------------------
    def visit_For(self, node: ast.For):
        if "GL106" in self.enabled and isinstance(
            node.iter, (ast.Set, ast.SetComp)
        ):
            self.emit(
                "GL106", node.iter.lineno,
                f"iteration over a set in jit region "
                f"`{self.fn.qualname}` — trace order is hash-dependent",
            )
        self.generic_visit(node)

    def _check_comp(self, node):
        if "GL106" in self.enabled:
            for gen in node.generators:
                if isinstance(gen.iter, (ast.Set, ast.SetComp)):
                    self.emit(
                        "GL106", gen.iter.lineno,
                        f"comprehension over a set in jit region "
                        f"`{self.fn.qualname}` — trace order is "
                        "hash-dependent",
                    )
        self.generic_visit(node)

    visit_ListComp = _check_comp
    visit_SetComp = _check_comp
    visit_DictComp = _check_comp
    visit_GeneratorExp = _check_comp

    # -- GL107: global/nonlocal ---------------------------------------
    def visit_Global(self, node: ast.Global):
        if "GL107" in self.enabled:
            self.emit(
                "GL107", node.lineno,
                f"`global {', '.join(node.names)}` in jit region "
                f"`{self.fn.qualname}`",
            )

    def visit_Nonlocal(self, node: ast.Nonlocal):
        if "GL107" in self.enabled:
            self.emit(
                "GL107", node.lineno,
                f"`nonlocal {', '.join(node.names)}` in jit region "
                f"`{self.fn.qualname}`",
            )

    # -- GL105: f-strings ---------------------------------------------
    def visit_JoinedStr(self, node: ast.JoinedStr):
        if (
            "GL105" in self.enabled
            and self.raise_depth == 0
            and any(
                isinstance(v, ast.FormattedValue) for v in node.values
            )
        ):
            self.emit(
                "GL105", node.lineno,
                f"f-string in jit region `{self.fn.qualname}` "
                "(outside raise/assert)",
            )
        self.generic_visit(node)

    # -- GL101/GL102/GL103: calls -------------------------------------
    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        name = _dotted(node.func)

        # attribute-form host syncs fire regardless of taint: these
        # methods have no legitimate trace-time use on array-like values
        if isinstance(node.func, ast.Attribute) and "GL101" in self.enabled:
            if node.func.attr in ("item", "tolist", "block_until_ready"):
                self.emit(
                    "GL101", node.lineno,
                    f".{node.func.attr}() in jit region "
                    f"`{self.fn.qualname}`",
                )
                return

        if not name:
            return
        resolved = _call_dotted_resolved(self.mod, name)

        if "GL101" in self.enabled:
            if resolved.endswith("jax.device_get") or name == "jax.device_get":
                self.emit(
                    "GL101", node.lineno,
                    f"jax.device_get() in jit region `{self.fn.qualname}`",
                )
                return
            if resolved.split(".")[0] in ("numpy",) and resolved.split(".")[-1] in (
                "asarray", "array"
            ):
                if any(self.taint.expr(a) for a in node.args):
                    self.emit(
                        "GL101", node.lineno,
                        f"{name}() on a traced value in jit region "
                        f"`{self.fn.qualname}`",
                    )
                    return

        if "GL102" in self.enabled and name in ("float", "int", "bool",
                                                "complex"):
            if node.args and self.taint.expr(node.args[0]):
                self.emit(
                    "GL102", node.lineno,
                    f"{name}() on a traced value in jit region "
                    f"`{self.fn.qualname}`",
                )
                return

        if "GL105" in self.enabled and name == "str" and self.raise_depth == 0:
            if node.args and self.taint.expr(node.args[0]):
                self.emit(
                    "GL105", node.lineno,
                    f"str() of a traced value in jit region "
                    f"`{self.fn.qualname}`",
                )
                return

        if "GL103" in self.enabled:
            if name in _IMPURE_BARE and name not in self.mod.top_defs:
                self.emit(
                    "GL103", node.lineno,
                    f"impure call {name}() in jit region "
                    f"`{self.fn.qualname}`",
                )
                return
            for cand in {name, resolved}:
                if any(cand.startswith(p) for p in _IMPURE_PREFIXES):
                    self.emit(
                        "GL103", node.lineno,
                        f"impure call {name}() in jit region "
                        f"`{self.fn.qualname}`",
                    )
                    return
                # stdlib `random.` — only when `random` is not an alias
                # for jax.random
                if cand.startswith("random.") and not resolved.startswith(
                    "jax.random"
                ):
                    self.emit(
                        "GL103", node.lineno,
                        f"host RNG call {name}() in jit region "
                        f"`{self.fn.qualname}`",
                    )
                    return


# -- GL201: donation on step-like jit entry points ----------------------


class _DonateChecker(ast.NodeVisitor):
    def __init__(self, mod: _Mod, enabled: Set[str], emit) -> None:
        self.mod = mod
        self.enabled = enabled
        self.emit = emit

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        if "GL201" not in self.enabled:
            return
        name = _dotted(node.func)
        if not name or name.split(".")[-1] not in ("jit", "pjit"):
            return
        if name.split(".")[0] not in ("jax", "jit", "pjit"):
            return
        if not node.args:
            return
        target = node.args[0]
        tname = None
        if isinstance(target, ast.Name):
            tname = target.id
        elif isinstance(target, ast.Call):
            tname = _dotted(target.func)
        elif isinstance(target, ast.Attribute):
            tname = _dotted(target)
        if not tname:
            return  # lambdas etc.: nothing nameable to hold a policy on
        short = tname.split(".")[-1]
        if not _DONATE_NAME_RE.search(short) or _DONATE_EXEMPT_RE.search(short):
            return
        kws = {kw.arg for kw in node.keywords}
        if not ({"donate_argnums", "donate_argnames"} & kws):
            self.emit(
                "GL201", node.lineno,
                f"jax.jit({tname}, ...) — a step-like entry point "
                "jitted without donate_argnums",
            )

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self.generic_visit(node)
        if "GL201" not in self.enabled:
            return
        short = node.name
        if not _DONATE_NAME_RE.search(short) or _DONATE_EXEMPT_RE.search(short):
            return
        for dec in node.decorator_list:
            d = dec.func if isinstance(dec, ast.Call) else dec
            dname = _dotted(d) or ""
            if dname.split(".")[-1] in ("jit", "pjit") and dname.split(
                "."
            )[0] in ("jax", "jit", "pjit"):
                has_donate = isinstance(dec, ast.Call) and any(
                    kw.arg in ("donate_argnums", "donate_argnames")
                    for kw in dec.keywords
                )
                if not has_donate:
                    self.emit(
                        "GL201", dec.lineno,
                        f"@{dname} on step-like `{node.name}` without "
                        "donate_argnums",
                    )
            elif isinstance(dec, ast.Call) and dname.split(".")[-1] == "partial":
                if dec.args and (_dotted(dec.args[0]) or "").split(".")[-1] in (
                    "jit", "pjit"
                ):
                    if not any(
                        kw.arg in ("donate_argnums", "donate_argnames")
                        for kw in dec.keywords
                    ):
                        self.emit(
                            "GL201", dec.lineno,
                            f"@partial(jax.jit, ...) on step-like "
                            f"`{node.name}` without donate_argnums",
                        )


# -- GL202: host syncs inside step-dispatch loops -----------------------


class _StepLoopChecker(ast.NodeVisitor):
    """Flags blocking syncs in loops that drive a jitted step. Applies
    to HOST functions only (jit regions get the stricter GL1xx)."""

    def __init__(self, fn: _Func, enabled: Set[str], emit) -> None:
        self.fn = fn
        self.enabled = enabled
        self.emit = emit
        self.loop_depth = 0  # inside a step-dispatching loop?
        self._body_owner = fn.node

    def visit_FunctionDef(self, node):
        if node is self._body_owner:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        if node is self._body_owner:
            self.visit(node.body)

    @staticmethod
    def _loop_dispatches_step(node) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                name = _dotted(sub.func)
                if name and _STEP_CALL_RE.search(name.split(".")[-1]):
                    return True
        return False

    def _visit_loop(self, node) -> None:
        dispatches = self._loop_dispatches_step(node)
        if dispatches:
            self.loop_depth += 1
        self.generic_visit(node)
        if dispatches:
            self.loop_depth -= 1

    visit_For = _visit_loop
    visit_While = _visit_loop

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        if "GL202" not in self.enabled or self.loop_depth == 0:
            return
        name = _dotted(node.func)
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
            self.emit(
                "GL202", node.lineno,
                f".item() inside the step loop of `{self.fn.qualname}`",
            )
            return
        if not name:
            return
        if name in ("float", "int") and node.args and not isinstance(
            node.args[0], ast.Constant
        ):
            self.emit(
                "GL202", node.lineno,
                f"{name}() host sync inside the step loop of "
                f"`{self.fn.qualname}`",
            )
            return
        resolved = _call_dotted_resolved(self.fn.module, name)
        if name == "jax.device_get" or resolved == "jax.device_get":
            self.emit(
                "GL202", node.lineno,
                f"jax.device_get() inside the step loop of "
                f"`{self.fn.qualname}`",
            )


# -- GL301: serving lock discipline -------------------------------------


class _LockDisciplineChecker:
    """Per-class: find lock attributes created in __init__, then flag
    attribute mutations outside `with self.<lock>` when the attribute
    is shared across methods."""

    def __init__(self, mod: _Mod, enabled: Set[str], emit) -> None:
        self.mod = mod
        self.enabled = enabled
        self.emit = emit

    def run(self) -> None:
        if "GL301" not in self.enabled:
            return
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.ClassDef):
                self._check_class(node)

    def _lock_attrs(self, cls: ast.ClassDef) -> Set[str]:
        locks: Set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            vname = _dotted(node.value.func) or ""
            if vname.split(".")[-1] not in _LOCK_FACTORIES:
                continue
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    locks.add(t.attr)
        return locks

    @staticmethod
    def _self_attr(node: ast.AST) -> Optional[str]:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr
        return None

    def _check_class(self, cls: ast.ClassDef) -> None:
        locks = self._lock_attrs(cls)
        if not locks:
            return
        methods = [
            n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        # which methods touch which self attributes (read or write)
        touched_by: Dict[str, Set[str]] = {}
        writes: List[Tuple[str, ast.AST, int, bool]] = []
        for meth in methods:
            guarded_lines = self._guarded_lines(meth, locks)
            for node in ast.walk(meth):
                attr = None
                is_write = False
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        a = self._self_attr(t)
                        if a:
                            attr, is_write = a, True
                            break
                elif isinstance(node, ast.AugAssign):
                    a = self._self_attr(node.target)
                    if a:
                        attr, is_write = a, True
                elif isinstance(node, ast.Attribute):
                    attr = self._self_attr(node)
                if attr is None or attr in locks:
                    continue
                touched_by.setdefault(attr, set()).add(meth.name)
                if is_write and meth.name != "__init__":
                    writes.append((
                        attr, node, node.lineno,
                        node.lineno in guarded_lines,
                    ))
        for attr, _node, line, guarded in writes:
            if guarded:
                continue
            if len(touched_by.get(attr, ())) < 2:
                continue  # single-method private state: not shared
            lock_names = " / ".join(
                f"self.{name}" for name in sorted(locks)
            )
            self.emit(
                "GL301", line,
                f"`self.{attr}` mutated outside `with {lock_names}` in "
                f"{cls.name} (attribute is shared across "
                f"{len(touched_by[attr])} methods)",
            )

    def _guarded_lines(self, meth, locks: Set[str]) -> Set[int]:
        """Line numbers lexically inside `with self.<lock>:` blocks."""
        out: Set[int] = set()
        for node in ast.walk(meth):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                a = self._self_attr(item.context_expr)
                if a in locks:
                    end = getattr(node, "end_lineno", node.lineno)
                    out.update(range(node.lineno, end + 1))
                    break
        return out


# -- driver -------------------------------------------------------------


def _iter_py_files(paths: Sequence[str]) -> List[Tuple[str, str, str]]:
    """(abspath, display_relpath, modname) for every .py under paths."""
    out = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p) and p.endswith(".py"):
            # keep ONE parent component so directory-scoped rules
            # (GL301: serving/) apply identically when a file is
            # spot-linted (`graftlint pkg/serving/server.py`) — and
            # same-basename file args stay distinguishable
            parent = os.path.basename(os.path.dirname(p))
            rel = (
                os.path.join(parent, os.path.basename(p))
                if parent else os.path.basename(p)
            )
            out.append((p, rel, rel[:-3].replace(os.sep, ".")))
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(
                d for d in dirnames
                if d != "__pycache__" and not d.startswith(".")
            )
            for f in sorted(filenames):
                if not f.endswith(".py"):
                    continue
                full = os.path.join(dirpath, f)
                rel = os.path.relpath(full, os.path.dirname(p))
                out.append((full, rel, _modname_for(os.path.dirname(p), full)))
    return out


def _modname_for(root: str, path: str) -> str:
    rel = os.path.relpath(path, root)
    mod = rel[:-3].replace(os.sep, ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


@dataclass
class LintResult:
    findings: List[Finding]
    files_scanned: int
    jit_regions: int
    parse_errors: List[str] = field(default_factory=list)

    @property
    def active(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    def as_dict(self) -> dict:
        return {
            "graftlint": 1,
            "files_scanned": self.files_scanned,
            "jit_regions": self.jit_regions,
            "parse_errors": list(self.parse_errors),
            "rules": sorted(RULES_BY_ID),
            "summary": {
                "total": len(self.findings),
                "active": len(self.active),
                "suppressed": len(self.findings) - len(self.active),
            },
            "findings": [f.as_dict() for f in self.findings],
        }


def lint_paths(
    paths: Sequence[str],
    rules: Optional[Sequence[str]] = None,
    *,
    files: Optional[Sequence[Tuple[str, str, str]]] = None,
) -> LintResult:
    """Lint every .py file under ``paths``; returns all findings
    (suppressed ones flagged, not dropped — the JSON output shows
    them so a suppression is an auditable decision, not a deletion).

    ``files`` (pre-enumerated ``_iter_py_files`` tuples) skips the
    directory walk — the CLI already walked each path for its
    empty-path guard and must not do the I/O twice."""
    enabled: Set[str] = (
        {resolve_rule_token(r) for r in rules}
        if rules else set(RULES_BY_ID)
    )
    files = list(files) if files is not None else _iter_py_files(paths)
    mods: Dict[str, _Mod] = {}
    parse_errors: List[str] = []
    for full, rel, modname in files:
        m = _load_module(full, rel, modname)
        if m is not None:
            # same-basename spot-lint args must BOTH be scanned, not
            # last-writer-wins (an order-dependent silent lint gap);
            # disambiguated keys make cross-module resolution of the
            # colliding name ambiguous, which _find_module treats as
            # unresolvable — safe under-approximation
            key, i = modname, 2
            while key in mods:
                key, i = f"{modname}#{i}", i + 1
            m.modname = key
            mods[key] = m
        else:
            # an unparseable file would otherwise be SILENTLY exempt
            # from every rule — surface it (callers decide severity)
            parse_errors.append(rel)

    _mark_roots(mods)
    regions = _reachable_jit_regions(mods)

    findings: List[Finding] = []

    def make_emit(mod: _Mod):
        def emit(rule: str, line: int, message: str) -> None:
            r = RULES_BY_ID[rule]
            # a suppression may sit on the reported line or anywhere in
            # the enclosing statement (multi-line calls)
            lines = _statement_lines(mod, line)
            findings.append(Finding(
                path=mod.relpath, line=line, rule=rule,
                message=message, hint=r.hint,
                suppressed=mod.suppressions.covers(rule, lines),
            ))
        return emit

    stmt_cache: Dict[str, List[Tuple[int, int]]] = {}

    def _statement_lines(mod: _Mod, line: int) -> List[int]:
        # keyed by ABSOLUTE path: two same-basename file args share a
        # display relpath (serving/x.py) but must not share spans, or
        # one file's suppression coverage silently applies the other's
        # statement extents
        spans = stmt_cache.get(mod.path)
        if spans is None:
            spans = []
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.stmt):
                    spans.append(
                        (node.lineno, getattr(node, "end_lineno", node.lineno))
                    )
            stmt_cache[mod.path] = spans
        best: Optional[Tuple[int, int]] = None
        for lo, hi in spans:
            if lo <= line <= hi and (
                best is None or (hi - lo) < (best[1] - best[0])
            ):
                best = (lo, hi)
        if best is None:
            return [line]
        return list(range(best[0], best[1] + 1))

    for mod in mods.values():
        emit = make_emit(mod)
        for fn in mod.funcs:
            if fn.key in regions:
                _JitRegionChecker(fn, enabled, emit).visit(fn.node)
            else:
                _StepLoopChecker(fn, enabled, emit).visit(fn.node)
        _DonateChecker(mod, enabled, emit).visit(mod.tree)
        # membership keyed on the lint-root-RELATIVE path (file args
        # keep one parent component, so spot-linting serving/server.py
        # still applies the rule) — never the absolute path, which
        # would drag a whole checkout under /home/serving/... into the
        # serving-only rules
        if "serving" in mod.relpath.split(os.sep):
            _LockDisciplineChecker(mod, enabled, emit).run()

    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return LintResult(
        findings=findings, files_scanned=len(mods),
        jit_regions=len(regions), parse_errors=sorted(parse_errors),
    )
