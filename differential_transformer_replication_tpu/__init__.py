"""TPU-native Differential Transformer framework.

A from-scratch JAX/XLA/Pallas/pjit framework with the capabilities of
``JoshFCooper415/differential_transformer_replication`` (see SURVEY.md):
three interchangeable decoder-only LMs (vanilla control, 2-term
differential, N-term alternating differential) behind a single
model-select switch, plus a data-parallel training runtime, BPE data
pipeline, checkpointing, and fused Pallas differential flash attention.

Design stance (not a port): merged-head einsum attention instead of the
reference's per-head Python loops, pure-functional lambda scheduling
instead of in-place buffer writes, pytree parameters, bf16 compute with
fp32 state, and SPMD sharding over a `jax.sharding.Mesh`.
"""

__version__ = "0.1.0"

from differential_transformer_replication_tpu.config import ModelConfig, TrainConfig

__all__ = ["ModelConfig", "TrainConfig", "__version__"]
