"""Dynamic sanitizer tests: the recompile sentinel's counting/budget
semantics, the host-sync sentinel's guard behavior, and the two
acceptance pins the ISSUE names — the serving engine's decode step
compiles exactly ONCE across N mixed requests, and the sharded dp_step
compiles exactly ONCE across M optimizer steps — both asserted through
:class:`RecompileSentinel` (not just the jit cache-size counters, which
only see their own closure)."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.analysis.sanitizers import (
    HostSyncError,
    HostSyncSentinel,
    RecompileBudgetError,
    RecompileSentinel,
    compile_count,
)
from differential_transformer_replication_tpu.config import (
    MeshConfig,
    ModelConfig,
    ServingConfig,
    TrainConfig,
)
from differential_transformer_replication_tpu.obs.registry import Registry


def _fresh_jit():
    """A jit closure no other test shares (fresh function identity =
    cold cache), so compile counts here are deterministic."""
    return jax.jit(lambda x: x * 2.0 + 1.0)


class TestRecompileSentinel:
    def test_counts_fresh_compiles(self):
        f = _fresh_jit()
        with RecompileSentinel(budget=None, name="count") as s:
            f(jnp.ones((3,)))
            f(jnp.ones((5,)))  # second shape -> second compile
        assert s.count >= 2

    def test_cached_calls_count_zero(self):
        f = _fresh_jit()
        x = jnp.ones((7,))
        f(x)  # warm outside the window
        with RecompileSentinel(budget=0, name="warm") as s:
            for _ in range(5):
                f(x)
        assert s.count == 0

    def test_budget_exceeded_raises(self):
        f = _fresh_jit()
        with pytest.raises(RecompileBudgetError, match="retraces"):
            with RecompileSentinel(budget=0, name="cold"):
                f(jnp.ones((9,)))

    def test_budget_allows_expected_compiles(self):
        f = _fresh_jit()
        # inputs built OUTSIDE the window (jnp.ones compiles per shape)
        a, b = jnp.ones((11,)), jnp.ones((13,))
        with RecompileSentinel(budget=2, name="two") as s:
            f(a)
            f(b)
        assert 1 <= s.count <= 2

    def test_body_exception_wins_over_budget(self):
        f = _fresh_jit()
        with pytest.raises(ValueError, match="body"):
            with RecompileSentinel(budget=0, name="err"):
                f(jnp.ones((15,)))
                raise ValueError("body")

    def test_registry_reporting(self):
        reg = Registry()
        f = _fresh_jit()
        with pytest.raises(RecompileBudgetError):
            with RecompileSentinel(budget=0, name="win", registry=reg):
                f(jnp.ones((17,)))
        text = reg.render()
        assert 'analysis_compiles_in_window{window="win"}' in text
        assert (
            'analysis_recompile_violations_total{window="win"} 1' in text
        )

    def test_compile_count_monotone(self):
        a = compile_count()
        _fresh_jit()(jnp.ones((19,)))
        assert compile_count() > a

    def test_counts_compiles_from_other_threads(self):
        # the engine compiles on its runner thread; the sentinel must
        # see process-wide events, not thread-local ones
        f = _fresh_jit()

        def work():
            f(jnp.ones((21,)))

        with RecompileSentinel(budget=None, name="thread") as s:
            t = threading.Thread(target=work)
            t.start()
            t.join()
        assert s.count >= 1


class TestHostSyncSentinel:
    def test_item_trips_guard(self):
        x = jnp.arange(4.0)
        with pytest.raises(Exception):  # jax's guard error type
            with HostSyncSentinel():
                (x * 2).item()

    def test_device_get_raises_typed(self):
        x = jnp.arange(4.0)
        with pytest.raises(HostSyncError, match="no-sync window"):
            with HostSyncSentinel():
                jax.device_get(x)

    def test_allow_window_sanctions_syncs(self):
        x = jnp.arange(4.0)
        with HostSyncSentinel() as guard:
            y = x * 3
            with guard.allow():
                v = jax.device_get(y)
        assert v[1] == 3.0

    def test_log_mode_counts_without_raising(self):
        reg = Registry()
        x = jnp.arange(4.0)
        with HostSyncSentinel(mode="log", registry=reg,
                              name="logwin") as guard:
            jax.device_get(x)
        assert guard.violations == 1
        assert (
            'analysis_host_sync_violations_total{window="logwin"} 1'
            in reg.render()
        )

    def test_device_get_restored_after_exit(self):
        orig = jax.device_get
        with HostSyncSentinel(mode="log"):
            assert jax.device_get is not orig
        assert jax.device_get is orig
        # and restored even when the window raises
        try:
            with HostSyncSentinel():
                jax.device_get(jnp.ones(2))
        except HostSyncError:
            pass
        assert jax.device_get is orig

    def test_clean_window_passes(self):
        x = jnp.arange(8.0)
        f = jax.jit(lambda v: jnp.sum(v * v))
        f(x)  # warm (compile does internal transfers on CPU)
        with HostSyncSentinel() as guard:
            y = f(x)  # pure device work: no host sync
        assert guard.violations == 0
        assert float(y) == float(np.sum(np.arange(8.0) ** 2))

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError):
            HostSyncSentinel(mode="warn")


# -- the two acceptance pins -------------------------------------------


def _tiny_engine():
    from differential_transformer_replication_tpu.models import init_model
    from differential_transformer_replication_tpu.serving import (
        ServingEngine,
    )

    cfg = ModelConfig(
        model="control", vocab_size=61, n_embd=32, n_head=2, n_layer=2,
        block_size=32, dropout=0.0, compute_dtype="float32",
    )
    params = init_model(jax.random.PRNGKey(0), cfg)
    serving = ServingConfig(num_slots=4, prefill_chunk=8,
                            prefill_budget=16)
    return ServingEngine(params, cfg, serving), cfg


class TestEngineDecodePin:
    def test_decode_compiles_once_across_mixed_requests(self):
        """The ROADMAP's 'one jitted full-pool decode step' invariant,
        pinned dynamically: after one warmup request has compiled the
        ladder, N requests with mixed lengths, temperatures, seeds and
        arrival order add ZERO compilations — and the decode closure's
        own cache holds exactly one entry."""
        engine, cfg = _tiny_engine()
        rng = np.random.default_rng(3)
        # warmup: one request per prefill-ladder size (1,2,4,8), so
        # every chunk shape + the decode/sampler kernels are compiled
        for n in (1, 2, 4, 8):
            engine.submit(rng.integers(0, 61, size=n).tolist(),
                          max_new_tokens=2, temperature=1.0, seed=0)
        engine.run()
        assert engine.compile_stats()["decode"] == 1

        with RecompileSentinel(budget=0, name="engine-decode") as s:
            # mixed lengths (every chunking of the warmed ladder),
            # greedy + sampled + top-k rows sharing the pool, staggered
            # admission so slots churn
            outs = []
            for i, n in enumerate((3, 8, 5, 1, 7, 6, 2, 4)):
                engine.submit(
                    rng.integers(0, 61, size=n).tolist(),
                    max_new_tokens=3 + (i % 3),
                    temperature=0.0 if i % 2 else 1.3,
                    top_k=5 if i % 3 == 0 else None,
                    seed=i,
                )
                outs.extend(engine.step())  # interleave admit + decode
            outs.extend(engine.run())
        assert s.count == 0, "mixed traffic must not recompile anything"
        assert len(outs) == 8
        assert engine.compile_stats()["decode"] == 1

    def test_restart_adds_zero_compiles(self):
        engine, cfg = _tiny_engine()
        engine.submit([1, 2, 3], max_new_tokens=2)
        engine.run()
        with RecompileSentinel(budget=0, name="engine-restart"):
            engine.reset_after_crash()
            engine.submit([4, 5], max_new_tokens=2)
            engine.run()


class TestDpStepPin:
    def test_dp_step_compiles_once_across_steps(self):
        """ROADMAP invariant for the training hot path: the sharded
        dp_step compiles exactly once; M further steps (including
        fresh batch values) add zero compilations."""
        from differential_transformer_replication_tpu.parallel import (
            create_mesh,
            make_sharded_train_step,
        )
        from differential_transformer_replication_tpu.parallel.dp_step import (
            create_sharded_train_state,
        )

        mesh_cfg = MeshConfig(data=8)
        cfg = TrainConfig(
            model=ModelConfig(
                model="diff", vocab_size=128, n_embd=32, n_head=2,
                n_layer=2, block_size=16, dropout=0.0,
                compute_dtype="float32",
            ),
            mesh=mesh_cfg, vocab_size=128, learning_rate=1e-2,
            min_lr=1e-3, warmup_iters=2, max_iters=100,
        )
        mesh = create_mesh(mesh_cfg)
        state = create_sharded_train_state(
            jax.random.PRNGKey(0), cfg, mesh
        )
        step = make_sharded_train_step(cfg, mesh, state)

        def batch(seed):
            x = jax.random.randint(
                jax.random.PRNGKey(seed), (1, 8, 16), 0, 128
            )
            return {"x": x, "y": jnp.roll(x, -1, axis=-1)}

        with RecompileSentinel(budget=None, name="dp-warm") as warm:
            state, _ = step(state, batch(0), None)
        assert warm.count >= 1  # the one real compile

        with RecompileSentinel(budget=0, name="dp-steady") as s:
            for i in range(1, 4):
                state, metrics = step(state, batch(i), None)
        assert s.count == 0
        # the wrapper's own cache agrees (what the trainer's
        # compile-event counter reads)
        if hasattr(step, "_cache_size"):
            assert step._cache_size() == 1
        assert np.isfinite(float(jax.device_get(metrics["loss"])))
