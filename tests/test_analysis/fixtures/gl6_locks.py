"""graftlint GL6xx fixture — planted lock-order and blocking hazards.

NEVER imported or executed: tests/test_lint_clean.py lints this file to
prove the GL6xx passes fire (anti-vacuity)."""

import queue
import threading
import time


class Inverted:
    """PLANTED GL601: _a -> _b in one(), _b -> _a in two()."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.n = 0

    def one(self):
        with self._a:
            with self._b:
                self.n += 1

    def two(self):
        with self._b:
            with self._a:
                self.n -= 1


class BlockedUnderLock:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self._done = threading.Event()

    def sleepy(self):
        with self._lock:
            # PLANTED GL602: sleeping while every other thread waits
            time.sleep(0.5)

    def queue_get(self):
        with self._lock:
            # PLANTED GL602: unbounded queue get under the lock
            return self._q.get()

    def bounded_ok(self):
        with self._lock:
            # negative twin: bounded get releases within the timeout
            return self._q.get(timeout=0.1)


class Ordered:
    """Negative twin: consistent _a -> _b order everywhere."""

    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.n = 0

    def one(self):
        with self._a:
            with self._b:
                self.n += 1

    def two(self):
        with self._a:
            with self._b:
                self.n -= 1
