"""graftlint GL4xx fixture — planted sharding/collective hazards.

NEVER imported or executed: tests/test_lint_clean.py lints this file to
prove the GL4xx passes fire (anti-vacuity). Each planted hazard is
labeled; the clean twin below it pins the negative."""

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map


def unbound_collective(x):
    # PLANTED GL401: no shard_map/pmap context reaches this function
    return jax.lax.psum(x, "data")


def wrong_axis_body(x):
    # PLANTED GL401: pmap below binds "data", not "model"
    return jax.lax.pmean(x, "model")


wrong_axis = jax.pmap(wrong_axis_body, axis_name="data")


def branchy_body(x, pred):
    def diverging_arm(v):
        # PLANTED GL402: collective under a lax.cond arm
        return jax.lax.psum(v, "data")

    def safe_arm(v):
        return v * 2.0

    return jax.lax.cond(pred, diverging_arm, safe_arm, x)


def transfer_body(x):
    # PLANTED GL403: device_put inside a shard_map body
    y = jax.device_put(x)
    return jnp.sum(y)


branchy = shard_map(branchy_body, mesh=None, in_specs=None, out_specs=None)
transfer = shard_map(transfer_body, mesh=None, in_specs=None, out_specs=None)


def clean_body(x):
    # negative twin: bound by the shard_map below — must NOT fire
    return jax.lax.psum(x, "data") + jax.lax.axis_index("data")


clean = shard_map(clean_body, mesh=None, in_specs=None, out_specs=None)


def suppressed_collective(x):
    # suppression plumbing for the family stays auditable
    return jax.lax.pmax(x, "data")  # graftlint: disable=GL401 (fixture)
