"""graftlint GL5xx fixture — planted Pallas-kernel hazards.

NEVER imported or executed: tests/test_lint_clean.py lints this file to
prove the GL5xx passes fire (anti-vacuity)."""

import time

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def ragged_blocks(x):
    # PLANTED GL501: 100 % 48 != 0 on the out spec's first dim
    return pl.pallas_call(
        _copy_kernel,
        grid=(3,),
        in_specs=[pl.BlockSpec((48, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((48, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((100, 128), jnp.float32),
    )(x)


def _bf16_acc_kernel(x_ref, o_ref, acc_ref):
    # PLANTED GL502: multiply-accumulate into the bf16 scratch below
    acc_ref[...] += x_ref[...] * 2.0
    o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def bf16_accumulator(x, rows):
    return pl.pallas_call(
        _bf16_acc_kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((128, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((128, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, 128), jnp.float32),
        scratch_shapes=[pltpu.VMEM((128, 128), jnp.bfloat16)],
    )(x)


def vmem_hog(x, rows):
    # PLANTED GL503 (warning): 2048*4096 fp32 scratch = 32 MiB > 16 MiB
    return pl.pallas_call(
        _copy_kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((128, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((128, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, 128), jnp.float32),
        scratch_shapes=[pltpu.VMEM((2048, 4096), jnp.float32)],
    )(x)


def impure_and_closing(x):
    y = jnp.sum(x)

    def _impure_kernel(x_ref, o_ref):
        # PLANTED GL504 (impure call in kernel body)
        t = time.time()
        # PLANTED GL504 (closure over traced `y` from enclosing scope)
        o_ref[...] = x_ref[...] + y + t

    return pl.pallas_call(
        _impure_kernel,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
    )(x)


def clean_call(x, rows):
    # negative twin: divisible blocks, fp32 scratch, pure kernel
    def _acc_kernel(x_ref, o_ref, acc_ref):
        acc_ref[...] += x_ref[...] * 2.0
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)

    return pl.pallas_call(
        _acc_kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((128, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((128, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, 128), jnp.float32),
        scratch_shapes=[pltpu.VMEM((128, 128), jnp.float32)],
    )(x)
