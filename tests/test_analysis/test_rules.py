"""graftlint rule-engine tests: per-rule positive + negative +
suppressed fixtures, jit-region discovery (decorators, call sites,
maker idiom, cross-module reachability through re-exports), and the
CLI's machine-parseable ``--json`` contract.

Every rule in analysis/rules.py has a POSITIVE fixture here proving it
fires — the acceptance contract: a rule that cannot fire is dead
weight, and a rule that fires on clean idioms would poison the
clean-tree gate (tests/test_lint_clean.py)."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from differential_transformer_replication_tpu.analysis import (
    RULES,
    RULES_BY_ID,
    lint_paths,
)

REPO = Path(__file__).resolve().parents[2]
GRAFTLINT = REPO / "tools" / "graftlint.py"


def lint_src(tmp_path, src, filename="mod.py", rules=None):
    """Write one fixture module and lint the directory; returns the
    list of ACTIVE finding rule ids (sorted, duplicates kept)."""
    path = tmp_path / filename
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(src)
    result = lint_paths([str(tmp_path)], rules=rules)
    return result


def active_ids(result):
    return sorted(f.rule for f in result.active)


def all_ids(result):
    return sorted(f.rule for f in result.findings)


JIT_HEADER = "import jax\nimport jax.numpy as jnp\n"


class TestRuleCatalog:
    def test_at_least_eight_distinct_rules(self):
        assert len(RULES) >= 8
        assert len({r.id for r in RULES}) == len(RULES)

    def test_every_rule_documented(self):
        for r in RULES:
            assert r.summary and r.hint, f"{r.id} missing docs"


class TestGL101HostSync:
    def test_positive_item(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    v = jnp.sum(x)\n"
            "    return v.item()\n"
        ))
        assert "GL101" in active_ids(res)

    def test_positive_device_get(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    return jax.device_get(x)\n"
        ))
        assert "GL101" in active_ids(res)

    def test_positive_np_asarray_on_traced(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "import numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    s = jnp.sum(x)\n"
            "    return np.asarray(s)\n"
        ))
        assert "GL101" in active_ids(res)

    def test_negative_outside_jit(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def host(x):\n"
            "    return x.item()\n"
        ))
        assert "GL101" not in active_ids(res)

    def test_negative_np_asarray_on_host_value(self, tmp_path):
        # np.asarray of an untraced (host) value in a jit region is a
        # trace-time constant, not a sync
        res = lint_src(tmp_path, JIT_HEADER + (
            "import numpy as np\n"
            "@jax.jit\n"
            "def f(x, lens):\n"
            "    table = np.asarray([1, 2, 3])\n"
            "    return x + table\n"
        ))
        assert "GL101" not in active_ids(res)

    def test_suppressed(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    v = jnp.sum(x)\n"
            "    return v.item()  # graftlint: disable=GL101\n"
        ))
        assert "GL101" not in active_ids(res)
        assert "GL101" in all_ids(res)  # reported, flagged suppressed


class TestGL102HostCast:
    def test_positive(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    s = jnp.sum(x)\n"
            "    return float(s)\n"
        ))
        assert "GL102" in active_ids(res)

    def test_negative_static_cast(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x, cfg_scale):\n"
            "    n = float(x.shape[0])\n"  # shapes are static
            "    return x * n\n"
        ))
        assert "GL102" not in active_ids(res)


class TestGL103ImpureCall:
    def test_positive_time(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "import time\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x * time.time()\n"
        ))
        assert "GL103" in active_ids(res)

    def test_positive_np_random(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "import numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x + np.random.rand()\n"
        ))
        assert "GL103" in active_ids(res)

    def test_positive_print(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    print(x)\n"
            "    return x\n"
        ))
        assert "GL103" in active_ids(res)

    def test_negative_jax_random(self, tmp_path):
        # `from jax import random; random.normal(...)` is pure — the
        # alias must resolve to jax.random, not stdlib random
        res = lint_src(tmp_path, (
            "import jax\nfrom jax import random\n"
            "@jax.jit\n"
            "def f(key, x):\n"
            "    return x + random.normal(key, x.shape)\n"
        ))
        assert "GL103" not in active_ids(res)

    def test_negative_host_print(self, tmp_path):
        res = lint_src(tmp_path, (
            "def host():\n"
            "    print('hello')\n"
        ))
        assert "GL103" not in active_ids(res)


class TestGL104TracedBranch:
    def test_positive_if(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    s = jnp.sum(x)\n"
            "    if s > 0:\n"
            "        return x\n"
            "    return -x\n"
        ))
        assert "GL104" in active_ids(res)

    def test_positive_while(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    s = jnp.max(x)\n"
            "    while s > 0:\n"
            "        s = s - 1\n"
            "    return s\n"
        ))
        assert "GL104" in active_ids(res)

    def test_negative_static_config_branch(self, tmp_path):
        # branching on config/static values is the normal jit idiom
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x, n_micro=1):\n"
            "    if x.shape[0] == 1:\n"
            "        return x\n"
            "    return x * 2\n"
        ))
        assert "GL104" not in active_ids(res)

    def test_taint_propagates_through_arithmetic(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    s = jnp.sum(x)\n"
            "    t = s * 2 + 1\n"
            "    if t > 3:\n"
            "        return x\n"
            "    return -x\n"
        ))
        assert "GL104" in active_ids(res)

    def test_shape_access_strips_taint(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    h = jnp.reshape(x, (-1,))\n"
            "    if h.shape[0] > 4:\n"
            "        return h\n"
            "    return -h\n"
        ))
        assert "GL104" not in active_ids(res)

    def test_positive_branch_on_bare_parameter(self, tmp_path):
        # a jit root's params ARE the traced values — the canonical
        # hazard form must fire without any jnp call seeding taint
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def step_fn(x, y):\n"
            "    if x > 0:\n"
            "        return float(x)\n"
            "    return y\n"
        ))
        assert "GL104" in active_ids(res)
        assert "GL102" in active_ids(res)

    def test_positive_scan_body_param_while(self, tmp_path):
        # call-site roots (lax.scan body) get param seeding too
        res = lint_src(tmp_path, JIT_HEADER + (
            "def body(carry, t):\n"
            "    s = carry + t\n"
            "    while s > 0:\n"
            "        s = s - 1\n"
            "    return s, s\n"
            "out = jax.lax.scan(body, 0, None)\n"
        ))
        assert "GL104" in active_ids(res)

    def test_negative_attr_read_on_parameter(self, tmp_path):
        # config objects arrive as params; attribute reads on a bare
        # param stay static (if cfg.dropout > 0 is the normal idiom)
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x, cfg):\n"
            "    if cfg.dropout > 0:\n"
            "        return x * cfg.scale\n"
            "    return x\n"
        ))
        assert "GL104" not in active_ids(res)

    def test_negative_is_none_on_parameter(self, tmp_path):
        # identity tests never boolify a tracer
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x, mask):\n"
            "    if mask is not None:\n"
            "        x = x + mask\n"
            "    s = jnp.sum(x)\n"
            "    if s is None:\n"
            "        return x\n"
            "    return s\n"
        ))
        assert "GL104" not in active_ids(res)

    def test_negative_static_argnums_param(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "from functools import partial\n"
            "@partial(jax.jit, static_argnums=(1,))\n"
            "def f(x, n):\n"
            "    if n > 4:\n"
            "        return x * n\n"
            "    return x\n"
        ))
        assert "GL104" not in active_ids(res)

    def test_negative_static_argnames_call_site(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def f(x, n):\n"
            "    if n > 4:\n"
            "        return x * n\n"
            "    return x\n"
            "g = jax.jit(f, static_argnames=('n',))\n"
        ))
        assert "GL104" not in active_ids(res)

    def test_negative_helper_params_not_seeded(self, tmp_path):
        # transitively-reached helpers take host-static params (chunk
        # sizes, positions); only ROOT params are seeded
        res = lint_src(tmp_path, JIT_HEADER + (
            "def helper(x, chunk):\n"
            "    if chunk > 4:\n"
            "        return x[:chunk]\n"
            "    return x\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return helper(x, 8)\n"
        ))
        assert "GL104" not in active_ids(res)

    def test_param_rebound_to_host_value_drops_seed(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x, w):\n"
            "    w = 4\n"
            "    if w > 2:\n"
            "        return x * w\n"
            "    return x\n"
        ))
        assert "GL104" not in active_ids(res)


class TestGL105FString:
    def test_positive(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    s = jnp.max(x)\n"
            "    label = f'max={s}'\n"
            "    return x\n"
        ))
        assert "GL105" in active_ids(res)

    def test_negative_in_raise(self, tmp_path):
        # error messages at trace time run on static data — exempt
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    if x.shape[0] == 0:\n"
            "        raise ValueError(f'empty input {x.shape}')\n"
            "    return x\n"
        ))
        assert "GL105" not in active_ids(res)

    def test_negative_in_assert(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x, k):\n"
            "    assert x.shape[0] == k, f'bad shape {x.shape}'\n"
            "    return x\n"
        ))
        assert "GL105" not in active_ids(res)


class TestGL106SetIteration:
    def test_positive(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(params):\n"
            "    total = 0.0\n"
            "    for k in {'wq', 'wk', 'wv'}:\n"
            "        total = total + jnp.sum(params[k])\n"
            "    return total\n"
        ))
        assert "GL106" in active_ids(res)

    def test_positive_comprehension(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(params):\n"
            "    vals = [params[k] for k in {'a', 'b'}]\n"
            "    return vals[0]\n"
        ))
        assert "GL106" in active_ids(res)

    def test_negative_sorted_iteration(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(params):\n"
            "    total = 0.0\n"
            "    for k in ('wq', 'wk', 'wv'):\n"
            "        total = total + jnp.sum(params[k])\n"
            "    return total\n"
        ))
        assert "GL106" not in active_ids(res)


class TestGL107GlobalState:
    def test_positive(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "_cache = None\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    global _cache\n"
            "    _cache = x\n"
            "    return x\n"
        ))
        assert "GL107" in active_ids(res)

    def test_negative_host_global(self, tmp_path):
        res = lint_src(tmp_path, (
            "_cache = None\n"
            "def host(x):\n"
            "    global _cache\n"
            "    _cache = x\n"
        ))
        assert "GL107" not in active_ids(res)


class TestGL201MissingDonate:
    def test_positive_call_form(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def train_step(state, batch):\n"
            "    return state\n"
            "jitted = jax.jit(train_step)\n"
        ))
        assert "GL201" in active_ids(res)

    def test_positive_decorator_form(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def decode_step(pool, tokens):\n"
            "    return pool\n"
        ))
        assert "GL201" in active_ids(res)

    def test_negative_with_donate(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "from functools import partial\n"
            "def train_step(state, batch):\n"
            "    return state\n"
            "jitted = jax.jit(train_step, donate_argnums=(0,))\n"
            "@partial(jax.jit, donate_argnums=(0,))\n"
            "def update_step(state):\n"
            "    return state\n"
        ))
        assert "GL201" not in active_ids(res)

    def test_negative_eval_exempt(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def eval_step(params, x):\n"
            "    return params\n"
        ))
        assert "GL201" not in active_ids(res)

    def test_negative_maker_call_with_donate(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def make_step_fn(cfg):\n"
            "    def step(state, batch):\n"
            "        return state\n"
            "    return step\n"
            "jitted = jax.jit(make_step_fn(None), donate_argnums=(0,))\n"
        ))
        assert "GL201" not in active_ids(res)


class TestGL202SyncInStepLoop:
    def test_positive(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def train(step, state, batch):\n"
            "    for i in range(100):\n"
            "        state, metrics = step(state, batch)\n"
            "        loss = float(metrics['loss'])\n"
            "    return loss\n"
        ))
        assert "GL202" in active_ids(res)

    def test_positive_device_get(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def train(train_step, state, batch):\n"
            "    while True:\n"
            "        state, metrics = train_step(state, batch)\n"
            "        m = jax.device_get(metrics)\n"
        ))
        assert "GL202" in active_ids(res)

    def test_negative_outside_loop(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def train(step, state, batch):\n"
            "    for i in range(100):\n"
            "        state, metrics = step(state, batch)\n"
            "    return float(metrics['loss'])\n"
        ))
        assert "GL202" not in active_ids(res)

    def test_negative_loop_without_step(self, tmp_path):
        res = lint_src(tmp_path, (
            "def tally(xs):\n"
            "    total = 0.0\n"
            "    for x in xs:\n"
            "        total += float(x)\n"
            "    return total\n"
        ))
        assert "GL202" not in active_ids(res)

    def test_suppressed_with_trailing_why(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def train(step, state, batch):\n"
            "    for i in range(100):\n"
            "        state, metrics = step(state, batch)\n"
            "        if i % 50 == 0:\n"
            "            loss = float(metrics['loss'])  "
            "# graftlint: disable=GL202 (log-boundary sync)\n"
        ))
        assert "GL202" not in active_ids(res)
        assert "GL202" in all_ids(res)


class TestGL301LockDiscipline:
    POS = (
        "import threading\n"
        "class Runner:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.count = 0\n"
        "    def bump(self):\n"
        "        self.count += 1\n"
        "    def read(self):\n"
        "        with self._lock:\n"
        "            return self.count\n"
    )

    def test_positive_in_serving_dir(self, tmp_path):
        res = lint_src(tmp_path, self.POS, filename="serving/runner.py")
        assert "GL301" in active_ids(res)

    def test_negative_outside_serving(self, tmp_path):
        res = lint_src(tmp_path, self.POS, filename="train/runner.py")
        assert "GL301" not in active_ids(res)

    def test_positive_direct_file_invocation(self, tmp_path):
        # spot-linting ONE serving file must apply the same rules as
        # linting the directory (file args keep one parent component)
        path = tmp_path / "serving" / "runner.py"
        path.parent.mkdir(parents=True)
        path.write_text(self.POS)
        res = lint_paths([str(path)])
        assert "GL301" in active_ids(res)

    def test_negative_checkout_under_serving_parent(self, tmp_path):
        # a repo cloned at /somewhere/serving/repo must NOT have the
        # serving-only rule applied to its whole tree — membership is
        # lint-root-relative, never absolute
        root = tmp_path / "serving" / "repo"
        (root / "train").mkdir(parents=True)
        (root / "train" / "runner.py").write_text(self.POS)
        res = lint_paths([str(root)])
        assert "GL301" not in active_ids(res)

    def test_negative_guarded_write(self, tmp_path):
        res = lint_src(tmp_path, (
            "import threading\n"
            "class Runner:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.count = 0\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self.count += 1\n"
            "    def read(self):\n"
            "        with self._lock:\n"
            "            return self.count\n"
        ), filename="serving/runner.py")
        assert "GL301" not in active_ids(res)

    def test_negative_lockless_class_exempt(self, tmp_path):
        # classes that own no lock are single-threaded by design here
        res = lint_src(tmp_path, (
            "class Plain:\n"
            "    def __init__(self):\n"
            "        self.count = 0\n"
            "    def bump(self):\n"
            "        self.count += 1\n"
        ), filename="serving/plain.py")
        assert "GL301" not in active_ids(res)

    def test_threadsafe_alias_suppression(self, tmp_path):
        res = lint_src(tmp_path, (
            "import threading\n"
            "class Runner:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.count = 0\n"
            "    def bump(self):\n"
            "        self.count += 1  # graftlint: threadsafe (GIL pub)\n"
            "    def read(self):\n"
            "        with self._lock:\n"
            "            return self.count\n"
        ), filename="serving/runner.py")
        assert "GL301" not in active_ids(res)
        assert "GL301" in all_ids(res)


class TestJitRegionDiscovery:
    def test_call_site_transform_marks_root(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def body(x):\n"
            "    return x.item()\n"
            "jitted = jax.jit(body)\n"
        ))
        assert "GL101" in active_ids(res)

    def test_lax_scan_body_is_jit_region(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "from jax import lax\n"
            "def outer(xs):\n"
            "    def body(carry, x):\n"
            "        return carry, x.item()\n"
            "    return lax.scan(body, 0.0, xs)\n"
        ))
        assert "GL101" in active_ids(res)

    def test_maker_idiom_marks_returned_fn(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def make_step(cfg):\n"
            "    def step(state, batch):\n"
            "        s = jnp.sum(batch)\n"
            "        return state, float(s)\n"
            "    return step\n"
            "jitted = jax.jit(make_step(None), donate_argnums=(0,))\n"
        ))
        assert "GL102" in active_ids(res)

    def test_callee_reached_through_call_graph(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def helper(x):\n"
            "    return x.item()\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return helper(x)\n"
        ))
        assert "GL101" in active_ids(res)

    def test_cross_module_reachability(self, tmp_path):
        (tmp_path / "impl.py").write_text(
            "def deep_helper(x):\n"
            "    return x.item()\n"
        )
        res = lint_src(tmp_path, (
            "import jax\n"
            "from impl import deep_helper\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return deep_helper(x)\n"
        ), filename="main.py")
        assert "GL101" in active_ids(res)
        # the finding lands in the CALLEE's file
        f = next(x for x in res.active if x.rule == "GL101")
        assert f.path.endswith("impl.py")

    def test_unreached_helper_is_host_code(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def helper(x):\n"
            "    return x.item()\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x * 2\n"
        ))
        assert "GL101" not in active_ids(res)


class TestSuppressionSyntax:
    def test_disable_file(self, tmp_path):
        res = lint_src(tmp_path, (
            "# graftlint: disable-file=GL101\n"
        ) + JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.item()\n"
        ))
        assert "GL101" not in active_ids(res)
        assert "GL101" in all_ids(res)

    def test_disable_file_all(self, tmp_path):
        res = lint_src(tmp_path, (
            "# graftlint: disable-file\n"
        ) + JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    print(x)\n"
            "    return x.item()\n"
        ))
        assert not active_ids(res)

    def test_rule_name_token(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.item()  # graftlint: disable=host-sync-in-jit\n"
        ))
        assert "GL101" not in active_ids(res)

    def test_unknown_rule_token_is_inert(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.item()  # graftlint: disable=GL999\n"
        ))
        assert "GL101" in active_ids(res)

    def test_multiline_statement_suppressed_from_first_line(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    v = jax.device_get(  # graftlint: disable=GL101\n"
            "        x\n"
            "    )\n"
            "    return v\n"
        ))
        assert "GL101" not in active_ids(res)


class TestRuleFilter:
    def test_rules_option_limits_scope(self, tmp_path):
        src = JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    print(x)\n"
            "    return x.item()\n"
        )
        res = lint_src(tmp_path, src, rules=["GL103"])
        assert "GL103" in active_ids(res)
        assert "GL101" not in active_ids(res)


class TestSameBasenameArgs:
    def test_both_colliding_files_are_linted(self, tmp_path):
        # `graftlint a/util.py b/util.py` must scan BOTH (the old
        # last-writer-wins keying made the exit code order-dependent)
        bad = JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.item()\n"
        )
        clean = "def ok():\n    return 1\n"
        (tmp_path / "a").mkdir()
        (tmp_path / "b").mkdir()
        (tmp_path / "a" / "util.py").write_text(bad)
        (tmp_path / "b" / "util.py").write_text(clean)
        for order in (
            [tmp_path / "a" / "util.py", tmp_path / "b" / "util.py"],
            [tmp_path / "b" / "util.py", tmp_path / "a" / "util.py"],
        ):
            res = lint_paths([str(p) for p in order])
            assert res.files_scanned == 2
            assert "GL101" in active_ids(res), order

    def test_colliding_files_keep_their_own_suppression_spans(self, tmp_path):
        # both args display as serving/x.py; the statement-span cache
        # must stay per-FILE or one file's multi-line suppression is
        # checked against the other's statement extents
        plain = JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.item()\n"
        )
        suppressed = JIT_HEADER + (
            "@jax.jit\n"
            "def g(y):\n"
            "    v = (\n"
            "        y.item()\n"
            "    )  # graftlint: disable=GL101 (fixture)\n"
            "    return v\n"
        )
        (tmp_path / "a" / "serving").mkdir(parents=True)
        (tmp_path / "b" / "serving").mkdir(parents=True)
        (tmp_path / "a" / "serving" / "x.py").write_text(plain)
        (tmp_path / "b" / "serving" / "x.py").write_text(suppressed)
        res = lint_paths([
            str(tmp_path / "a" / "serving" / "x.py"),
            str(tmp_path / "b" / "serving" / "x.py"),
        ])
        gl101 = [f for f in res.findings if f.rule == "GL101"]
        assert [f.suppressed for f in gl101] == [False, True]


SHARD_HEADER = (
    "import jax\nimport jax.numpy as jnp\n"
    "from jax.experimental.shard_map import shard_map\n"
)

PALLAS_HEADER = (
    "import jax\nimport jax.numpy as jnp\n"
    "from jax.experimental import pallas as pl\n"
    "from jax.experimental.pallas import tpu as pltpu\n"
)


class TestGL401UnboundCollective:
    def test_positive_no_binding_context(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def helper(x):\n"
            "    return jax.lax.psum(x, 'data')\n"
        ))
        assert "GL401" in active_ids(res)

    def test_positive_plain_jit_region(self, tmp_path):
        # jitted but NOT shard_mapped: the axis name is unbound at trace
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    return jax.lax.pmean(x, 'data')\n"
        ))
        assert "GL401" in active_ids(res)

    def test_negative_direct_shard_map_body(self, tmp_path):
        res = lint_src(tmp_path, SHARD_HEADER + (
            "def body(x):\n"
            "    return jax.lax.psum(x, 'data')\n"
            "f = shard_map(body, mesh=None, in_specs=None, out_specs=None)\n"
        ))
        assert "GL401" not in active_ids(res)

    def test_negative_axis_index_in_body(self, tmp_path):
        res = lint_src(tmp_path, SHARD_HEADER + (
            "def body(x):\n"
            "    return x + jax.lax.axis_index('data')\n"
            "f = shard_map(body, mesh=None, in_specs=None, out_specs=None)\n"
        ))
        assert "GL401" not in active_ids(res)

    def test_positive_pmap_wrong_axis(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def body(x):\n"
            "    return jax.lax.psum(x, 'model')\n"
            "f = jax.pmap(body, axis_name='data')\n"
        ))
        assert "GL401" in active_ids(res)

    def test_negative_pmap_right_axis(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def body(x):\n"
            "    return jax.lax.psum(x, 'data')\n"
            "f = jax.pmap(body, axis_name='data')\n"
        ))
        assert "GL401" not in active_ids(res)

    def test_negative_variable_axis_under_binder(self, tmp_path):
        # axis threaded in as a variable: bound by construction
        res = lint_src(tmp_path, SHARD_HEADER + (
            "def make(axis):\n"
            "    def body(x):\n"
            "        return jax.lax.pmean(x, axis)\n"
            "    return body\n"
            "def build(mesh):\n"
            "    return shard_map(make('data'), mesh=mesh, in_specs=None,\n"
            "                     out_specs=None)\n"
        ))
        assert "GL401" not in active_ids(res)

    def test_negative_wrapper_idiom(self, tmp_path):
        # body reaches shard_map only through a wrapper's parameter —
        # the compat.shard_map shape
        res = lint_src(tmp_path, SHARD_HEADER + (
            "def wrapper(fn, mesh):\n"
            "    return shard_map(fn, mesh=mesh, in_specs=None,\n"
            "                     out_specs=None)\n"
            "def body(x):\n"
            "    return jax.lax.pmean(x, 'data')\n"
            "def caller(mesh, x):\n"
            "    return wrapper(body, mesh)(x)\n"
        ))
        assert "GL401" not in active_ids(res)

    def test_negative_param_bound_lambda(self, tmp_path):
        # the dp_step shape: a pmean lambda handed into a maker whose
        # returned step runs under shard_map
        res = lint_src(tmp_path, SHARD_HEADER + (
            "def make_step(cfg, loss_sync=None):\n"
            "    def step(state, batch):\n"
            "        loss = jnp.sum(batch)\n"
            "        if loss_sync is not None:\n"
            "            loss = loss_sync(loss)\n"
            "        return state, loss\n"
            "    return step\n"
            "def build(cfg, mesh):\n"
            "    axis = 'data'\n"
            "    inner = make_step(cfg,\n"
            "                      loss_sync=lambda l: jax.lax.pmean(l, axis))\n"
            "    def raw(state, batch):\n"
            "        return inner(state, batch)\n"
            "    return shard_map(raw, mesh=mesh, in_specs=None,\n"
            "                     out_specs=None)\n"
        ))
        assert "GL401" not in active_ids(res)

    def test_negative_defvjp_backward(self, tmp_path):
        # a custom-vjp backward pmean is bound through the primal's
        # reachability (the _bucket_sync shape)
        res = lint_src(tmp_path, SHARD_HEADER + (
            "def make_sync(axis):\n"
            "    @jax.custom_vjp\n"
            "    def sync(t):\n"
            "        return t\n"
            "    def fwd(t):\n"
            "        return t, None\n"
            "    def bwd(_, ct):\n"
            "        return (jax.lax.pmean(ct, axis),)\n"
            "    sync.defvjp(fwd, bwd)\n"
            "    return sync\n"
            "def build(mesh):\n"
            "    sync = make_sync('data')\n"
            "    def body(x):\n"
            "        return sync(x)\n"
            "    return shard_map(body, mesh=mesh, in_specs=None,\n"
            "                     out_specs=None)\n"
        ))
        assert "GL401" not in active_ids(res)

    def test_positive_axis_kwarg_does_not_mask_name(self, tmp_path):
        # all_gather's `axis=` kwarg is the ARRAY dimension, not the
        # axis name — it must not clobber the positional name candidate
        res = lint_src(tmp_path, JIT_HEADER + (
            "def body(x):\n"
            "    return jax.lax.all_gather(x, 'mp', axis=0)\n"
            "f = jax.pmap(body, axis_name='dp')\n"
        ))
        assert "GL401" in active_ids(res)

    def test_suppressed(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def helper(x):\n"
            "    return jax.lax.psum(x, 'data')  "
            "# graftlint: disable=GL401 (fixture)\n"
        ))
        assert "GL401" not in active_ids(res)
        assert "GL401" in all_ids(res)


class TestGL402CollectiveUnderBranch:
    def test_positive_cond_arm(self, tmp_path):
        res = lint_src(tmp_path, SHARD_HEADER + (
            "def body(x, pred):\n"
            "    def yes(v):\n"
            "        return jax.lax.psum(v, 'data')\n"
            "    def no(v):\n"
            "        return v\n"
            "    return jax.lax.cond(pred, yes, no, x)\n"
            "f = shard_map(body, mesh=None, in_specs=None, out_specs=None)\n"
        ))
        assert "GL402" in active_ids(res)

    def test_positive_while_body(self, tmp_path):
        res = lint_src(tmp_path, SHARD_HEADER + (
            "def body(x):\n"
            "    def cond_fn(c):\n"
            "        return c[1] > 0\n"
            "    def body_fn(c):\n"
            "        return (jax.lax.pmean(c[0], 'data'), c[1] - 1)\n"
            "    return jax.lax.while_loop(cond_fn, body_fn, (x, 3))\n"
            "f = shard_map(body, mesh=None, in_specs=None, out_specs=None)\n"
        ))
        assert "GL402" in active_ids(res)

    def test_positive_transitively_reached(self, tmp_path):
        # the collective hides one call deep inside the arm
        res = lint_src(tmp_path, SHARD_HEADER + (
            "def deep(v):\n"
            "    return jax.lax.psum(v, 'data')\n"
            "def body(x, pred):\n"
            "    def yes(v):\n"
            "        return deep(v)\n"
            "    return jax.lax.cond(pred, yes, lambda v: v, x)\n"
            "f = shard_map(body, mesh=None, in_specs=None, out_specs=None)\n"
        ))
        assert "GL402" in active_ids(res)

    def test_negative_collective_outside_arm(self, tmp_path):
        res = lint_src(tmp_path, SHARD_HEADER + (
            "def body(x, pred):\n"
            "    s = jax.lax.psum(x, 'data')\n"
            "    return jax.lax.cond(pred, lambda v: v, lambda v: -v, s)\n"
            "f = shard_map(body, mesh=None, in_specs=None, out_specs=None)\n"
        ))
        assert "GL402" not in active_ids(res)

    def test_negative_scan_body_is_uniform(self, tmp_path):
        # scan/fori_loop trip counts are static — every shard runs the
        # same number of collectives (the ring-attention shape)
        res = lint_src(tmp_path, SHARD_HEADER + (
            "def body(ks):\n"
            "    def step(c, x):\n"
            "        return jax.lax.ppermute(c, 'sequence',\n"
            "                                [(0, 1), (1, 0)]), None\n"
            "    out, _ = jax.lax.scan(step, ks, None, length=4)\n"
            "    return out\n"
            "f = shard_map(body, mesh=None, in_specs=None, out_specs=None)\n"
        ))
        assert "GL402" not in active_ids(res)

    def test_suppressed(self, tmp_path):
        res = lint_src(tmp_path, SHARD_HEADER + (
            "def body(x, pred):\n"
            "    def yes(v):\n"
            "        return jax.lax.psum(v, 'data')  "
            "# graftlint: disable=GL402 (pred is pmean-uniform)\n"
            "    return jax.lax.cond(pred, yes, lambda v: v, x)\n"
            "f = shard_map(body, mesh=None, in_specs=None, out_specs=None)\n"
        ))
        assert "GL402" not in active_ids(res)
        assert "GL402" in all_ids(res)


class TestGL403HostTransferInShardBody:
    def test_positive(self, tmp_path):
        res = lint_src(tmp_path, SHARD_HEADER + (
            "def body(x):\n"
            "    return jax.device_put(x)\n"
            "f = shard_map(body, mesh=None, in_specs=None, out_specs=None)\n"
        ))
        assert "GL403" in active_ids(res)

    def test_negative_host_device_put(self, tmp_path):
        # placement BEFORE the shard_map call is the correct idiom
        res = lint_src(tmp_path, SHARD_HEADER + (
            "def body(x):\n"
            "    return x * 2\n"
            "def launch(mesh, x, sharding):\n"
            "    x = jax.device_put(x, sharding)\n"
            "    f = shard_map(body, mesh=mesh, in_specs=None,\n"
            "                  out_specs=None)\n"
            "    return f(x)\n"
        ))
        assert "GL403" not in active_ids(res)

    def test_suppressed(self, tmp_path):
        res = lint_src(tmp_path, SHARD_HEADER + (
            "def body(x):\n"
            "    return jax.device_put(x)  "
            "# graftlint: disable=GL403 (fixture)\n"
            "f = shard_map(body, mesh=None, in_specs=None, out_specs=None)\n"
        ))
        assert "GL403" not in active_ids(res)
        assert "GL403" in all_ids(res)


class TestGL501GridMismatch:
    POS = PALLAS_HEADER + (
        "def kern(x_ref, o_ref):\n"
        "    o_ref[...] = x_ref[...]\n"
        "def call(x):\n"
        "    return pl.pallas_call(\n"
        "        kern,\n"
        "        grid=(3,),\n"
        "        in_specs=[pl.BlockSpec((48, 128), lambda i: (i, 0))],\n"
        "        out_specs=pl.BlockSpec((48, 128), lambda i: (i, 0)),\n"
        "        out_shape=jax.ShapeDtypeStruct((100, 128), jnp.float32),\n"
        "    )(x)\n"
    )

    def test_positive(self, tmp_path):
        res = lint_src(tmp_path, self.POS)
        assert "GL501" in active_ids(res)

    def test_positive_through_module_constants(self, tmp_path):
        res = lint_src(tmp_path, PALLAS_HEADER + (
            "_ROWS = 100\n"
            "_BLOCK = 48\n"
            "def kern(x_ref, o_ref):\n"
            "    o_ref[...] = x_ref[...]\n"
            "def call(x):\n"
            "    return pl.pallas_call(\n"
            "        kern,\n"
            "        out_specs=pl.BlockSpec((_BLOCK, 128),\n"
            "                               lambda i: (i, 0)),\n"
            "        out_shape=jax.ShapeDtypeStruct((_ROWS, 128),\n"
            "                                       jnp.float32),\n"
            "    )(x)\n"
        ))
        assert "GL501" in active_ids(res)

    def test_negative_divisible(self, tmp_path):
        res = lint_src(tmp_path, self.POS.replace("(100, 128)", "(96, 128)"))
        assert "GL501" not in active_ids(res)

    def test_negative_dynamic_shapes(self, tmp_path):
        # non-static dims: a prover stays silent, never guesses
        res = lint_src(tmp_path, self.POS.replace(
            "def call(x):", "def call(x, M):"
        ).replace("(100, 128)", "(M, 128)"))
        assert "GL501" not in active_ids(res)

    def test_negative_nested_scope_constant_does_not_leak(self, tmp_path):
        # a sibling nested helper's local `BM = 100` is NOT the call
        # site's BM (module-level BM = 64 divides 256 evenly)
        res = lint_src(tmp_path, PALLAS_HEADER + (
            "BM = 64\n"
            "def kern(x_ref, o_ref):\n"
            "    o_ref[...] = x_ref[...]\n"
            "def call(x):\n"
            "    def helper():\n"
            "        BM = 100\n"
            "        return BM\n"
            "    return pl.pallas_call(\n"
            "        kern,\n"
            "        out_specs=pl.BlockSpec((BM, 128), lambda i: (i, 0)),\n"
            "        out_shape=jax.ShapeDtypeStruct((256, 128),\n"
            "                                       jnp.float32),\n"
            "    )(x)\n"
        ))
        assert "GL501" not in active_ids(res)

    def test_suppressed(self, tmp_path):
        res = lint_src(tmp_path, self.POS.replace(
            "        out_specs=pl.BlockSpec((48, 128), lambda i: (i, 0)),\n",
            "        out_specs=pl.BlockSpec((48, 128), lambda i: (i, 0)),  "
            "# graftlint: disable=GL501 (fixture)\n",
        ))
        assert "GL501" not in active_ids(res)
        assert "GL501" in all_ids(res)


class TestGL502SubFp32Accumulator:
    POS = PALLAS_HEADER + (
        "def kern(x_ref, o_ref, acc_ref):\n"
        "    acc_ref[...] += x_ref[...] * 2.0\n"
        "    o_ref[...] = acc_ref[...].astype(o_ref.dtype)\n"
        "def call(x, M):\n"
        "    return pl.pallas_call(\n"
        "        kern,\n"
        "        grid=(4,),\n"
        "        in_specs=[pl.BlockSpec((128, 128), lambda i: (i, 0))],\n"
        "        out_specs=pl.BlockSpec((128, 128), lambda i: (i, 0)),\n"
        "        out_shape=jax.ShapeDtypeStruct((M, 128), jnp.float32),\n"
        "        scratch_shapes=[pltpu.VMEM((128, 128), jnp.bfloat16)],\n"
        "    )(x)\n"
    )

    def test_positive(self, tmp_path):
        res = lint_src(tmp_path, self.POS)
        assert "GL502" in active_ids(res)

    def test_positive_star_refs_unpack(self, tmp_path):
        # the house kernel style: *refs + tuple unpack in the body
        res = lint_src(tmp_path, PALLAS_HEADER + (
            "def kern(*refs):\n"
            "    x_ref, o_ref, acc_ref = refs\n"
            "    acc_ref[...] = acc_ref[...] + x_ref[...] * 2.0\n"
            "    o_ref[...] = acc_ref[...].astype(o_ref.dtype)\n"
            "def call(x, M):\n"
            "    return pl.pallas_call(\n"
            "        kern,\n"
            "        in_specs=[pl.BlockSpec((128, 128), lambda i: (i, 0))],\n"
            "        out_specs=pl.BlockSpec((128, 128), lambda i: (i, 0)),\n"
            "        out_shape=jax.ShapeDtypeStruct((M, 128), jnp.float32),\n"
            "        scratch_shapes=[pltpu.VMEM((128, 128), jnp.bfloat16)],\n"
            "    )(x)\n"
        ))
        assert "GL502" in active_ids(res)

    def test_negative_fp32_scratch(self, tmp_path):
        res = lint_src(tmp_path, self.POS.replace("jnp.bfloat16", "jnp.float32"))
        assert "GL502" not in active_ids(res)

    def test_negative_bf16_scratch_without_accumulation(self, tmp_path):
        # sub-fp32 scratch used as a plain store target is legitimate
        res = lint_src(tmp_path, self.POS.replace(
            "    acc_ref[...] += x_ref[...] * 2.0\n",
            "    acc_ref[...] = x_ref[...] * 2.0\n",
        ))
        assert "GL502" not in active_ids(res)

    def test_suppressed(self, tmp_path):
        res = lint_src(tmp_path, self.POS.replace(
            "    acc_ref[...] += x_ref[...] * 2.0\n",
            "    acc_ref[...] += x_ref[...] * 2.0  "
            "# graftlint: disable=GL502 (fixture)\n",
        ))
        assert "GL502" not in active_ids(res)
        assert "GL502" in all_ids(res)


class TestGL503VmemBudget:
    POS = PALLAS_HEADER + (
        "def kern(x_ref, o_ref, acc_ref):\n"
        "    o_ref[...] = x_ref[...]\n"
        "def call(x, M):\n"
        "    return pl.pallas_call(\n"
        "        kern,\n"
        "        out_specs=pl.BlockSpec((128, 128), lambda i: (i, 0)),\n"
        "        out_shape=jax.ShapeDtypeStruct((M, 128), jnp.float32),\n"
        "        scratch_shapes=[pltpu.VMEM((2048, 4096), jnp.float32)],\n"
        "    )(x)\n"
    )

    def test_positive_is_warning(self, tmp_path):
        res = lint_src(tmp_path, self.POS)
        hits = [f for f in res.active if f.rule == "GL503"]
        assert hits and all(f.severity == "warning" for f in hits)
        # warn-severity findings never gate
        assert not res.gating

    def test_budget_configurable(self, tmp_path):
        from differential_transformer_replication_tpu.analysis.lint import (
            lint_paths as lp,
        )
        path = tmp_path / "mod.py"
        path.write_text(self.POS)
        res = lp([str(tmp_path)], vmem_budget_mib=64.0)
        assert "GL503" not in active_ids(res)

    def test_negative_small_blocks(self, tmp_path):
        res = lint_src(tmp_path, self.POS.replace("(2048, 4096)", "(128, 128)"))
        assert "GL503" not in active_ids(res)

    def test_suppressed(self, tmp_path):
        res = lint_src(tmp_path, self.POS.replace(
            "    return pl.pallas_call(\n",
            "    return pl.pallas_call(  "
            "# graftlint: disable=GL503 (fixture)\n",
        ))
        assert "GL503" not in active_ids(res)
        assert "GL503" in all_ids(res)


class TestGL504ImpureKernel:
    def test_positive_impure_call(self, tmp_path):
        res = lint_src(tmp_path, PALLAS_HEADER + (
            "import time\n"
            "def kern(x_ref, o_ref):\n"
            "    o_ref[...] = x_ref[...] * time.time()\n"
            "def call(x):\n"
            "    return pl.pallas_call(\n"
            "        kern,\n"
            "        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),\n"
            "    )(x)\n"
        ))
        ids = active_ids(res)
        assert "GL504" in ids
        assert "GL103" not in ids  # kernel impurity is GL504, not GL103

    def test_positive_impure_call_site_inside_jit_region(self, tmp_path):
        # the common real shape: the pallas_call SITE is itself jitted.
        # Kernel-ness must win — regular jit reachability stops at the
        # kernel, so the impure call reports GL504, not GL103
        res = lint_src(tmp_path, PALLAS_HEADER + (
            "import time\n"
            "def kern(x_ref, o_ref):\n"
            "    o_ref[...] = x_ref[...] * time.time()\n"
            "@jax.jit\n"
            "def call(x):\n"
            "    return pl.pallas_call(\n"
            "        kern,\n"
            "        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),\n"
            "    )(x)\n"
        ))
        ids = active_ids(res)
        assert "GL504" in ids
        assert "GL103" not in ids

    def test_positive_closure_over_traced(self, tmp_path):
        res = lint_src(tmp_path, PALLAS_HEADER + (
            "def call(x):\n"
            "    y = jnp.sum(x)\n"
            "    def kern(x_ref, o_ref):\n"
            "        o_ref[...] = x_ref[...] + y\n"
            "    return pl.pallas_call(\n"
            "        kern,\n"
            "        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),\n"
            "    )(x)\n"
        ))
        assert "GL504" in active_ids(res)

    def test_positive_index_map_closure(self, tmp_path):
        res = lint_src(tmp_path, PALLAS_HEADER + (
            "def kern(x_ref, o_ref):\n"
            "    o_ref[...] = x_ref[...]\n"
            "def call(x):\n"
            "    off = jnp.argmax(x)\n"
            "    return pl.pallas_call(\n"
            "        kern,\n"
            "        in_specs=[pl.BlockSpec((8, 128),\n"
            "                               lambda i: (i + off, 0))],\n"
            "        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),\n"
            "        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),\n"
            "    )(x)\n"
        ))
        assert "GL504" in active_ids(res)

    def test_negative_static_closure(self, tmp_path):
        # closing over shapes/ints from the enclosing scope is the
        # normal kernel idiom (block sizes, head counts)
        res = lint_src(tmp_path, PALLAS_HEADER + (
            "def call(x, block):\n"
            "    S, d = x.shape\n"
            "    def kern(x_ref, o_ref):\n"
            "        o_ref[...] = x_ref[...] * S\n"
            "    return pl.pallas_call(\n"
            "        kern,\n"
            "        in_specs=[pl.BlockSpec((block, d),\n"
            "                               lambda i: (i, 0))],\n"
            "        out_specs=pl.BlockSpec((block, d), lambda i: (i, 0)),\n"
            "        out_shape=jax.ShapeDtypeStruct((S, d), x.dtype),\n"
            "    )(x)\n"
        ))
        assert "GL504" not in active_ids(res)

    def test_negative_partial_bound_static(self, tmp_path):
        res = lint_src(tmp_path, PALLAS_HEADER + (
            "import functools\n"
            "def kern(x_ref, o_ref, *, scale):\n"
            "    o_ref[...] = x_ref[...] * scale\n"
            "def call(x):\n"
            "    return pl.pallas_call(\n"
            "        functools.partial(kern, scale=2.0),\n"
            "        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),\n"
            "    )(x)\n"
        ))
        assert "GL504" not in active_ids(res)

    def test_suppressed(self, tmp_path):
        res = lint_src(tmp_path, PALLAS_HEADER + (
            "import time\n"
            "def kern(x_ref, o_ref):\n"
            "    o_ref[...] = x_ref[...] * time.time()  "
            "# graftlint: disable=GL504 (fixture)\n"
            "def call(x):\n"
            "    return pl.pallas_call(\n"
            "        kern,\n"
            "        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),\n"
            "    )(x)\n"
        ))
        assert "GL504" not in active_ids(res)
        assert "GL504" in all_ids(res)


LOCKS_HEADER = "import threading\nimport queue\nimport time\n"


class TestGL601LockOrderInversion:
    POS = LOCKS_HEADER + (
        "class R:\n"
        "    def __init__(self):\n"
        "        self._a = threading.Lock()\n"
        "        self._b = threading.Lock()\n"
        "    def one(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "    def two(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n"
    )

    def test_positive_direct(self, tmp_path):
        res = lint_src(tmp_path, self.POS)
        assert "GL601" in active_ids(res)

    def test_positive_across_two_methods_via_call(self, tmp_path):
        # A->B through a method call, B->A lexical: the planted
        # inversion the acceptance list names
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class R:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "    def outer(self):\n"
            "        with self._a:\n"
            "            self.helper()\n"
            "    def helper(self):\n"
            "        with self._b:\n"
            "            pass\n"
            "    def other(self):\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                pass\n"
        ))
        assert "GL601" in active_ids(res)

    def test_positive_across_classes(self, tmp_path):
        # Outer holds _ol and calls into Inner (takes _il); Inner holds
        # _il and calls back through its owner ref (takes _ol) — the
        # cross-class cycle resolved via `self.x = Class(...)` typing
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class Outer:\n"
            "    def __init__(self):\n"
            "        self._ol = threading.Lock()\n"
            "        self.inner = Inner(self)\n"
            "    def fwd(self):\n"
            "        with self._ol:\n"
            "            self.inner.work()\n"
            "    def notify(self):\n"
            "        with self._ol:\n"
            "            pass\n"
            "class Inner:\n"
            "    def __init__(self, owner):\n"
            "        self._il = threading.Lock()\n"
            "        self.owner = Outer()\n"
            "    def work(self):\n"
            "        with self._il:\n"
            "            pass\n"
            "    def back(self):\n"
            "        with self._il:\n"
            "            self.owner.notify()\n"
        ), filename="locks.py")
        assert "GL601" in active_ids(res)

    def test_negative_one_directional_cross_class(self, tmp_path):
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class Inner:\n"
            "    def __init__(self):\n"
            "        self._il = threading.Lock()\n"
            "    def work(self):\n"
            "        with self._il:\n"
            "            pass\n"
            "class Outer:\n"
            "    def __init__(self):\n"
            "        self._ol = threading.Lock()\n"
            "        self.inner = Inner()\n"
            "    def fwd(self):\n"
            "        with self._ol:\n"
            "            self.inner.work()\n"
        ), filename="locks.py")
        assert "GL601" not in active_ids(res)

    def test_negative_consistent_order(self, tmp_path):
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class R:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "    def one(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
            "    def two(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
        ))
        assert "GL601" not in active_ids(res)

    def test_positive_unrelated_deep_chain_does_not_mask(self, tmp_path):
        # regression: a deep unrelated call chain must not poison the
        # acquisition analysis for a direct, shallow inversion
        deep = "".join(
            f"    def h{i}(self):\n        self.h{i + 1}()\n"
            for i in range(1, 7)
        )
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class R:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "    def deep_first(self):\n"
            "        self.h1()\n"
        ) + deep + (
            "    def h7(self):\n"
            "        with self._b:\n"
            "            pass\n"
            "    def shallow(self):\n"
            "        with self._a:\n"
            "            self.h7()\n"
            "    def inverted(self):\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                pass\n"
        ))
        assert "GL601" in active_ids(res)

    def test_negative_callback_defined_not_called(self, tmp_path):
        # a nested def ACQUIRING b runs later, outside the caller's
        # lock scope — defining it under `with self.a` is not a->b
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class R:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "    def m1(self):\n"
            "        with self._a:\n"
            "            return self.m2()\n"
            "    def m2(self):\n"
            "        def cb():\n"
            "            with self._b:\n"
            "                pass\n"
            "        return cb\n"
            "    def m3(self):\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                pass\n"
        ))
        assert "GL601" not in active_ids(res)

    def test_negative_nested_same_lock_rlock_style(self, tmp_path):
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class R:\n"
            "    def __init__(self):\n"
            "        self._a = threading.RLock()\n"
            "    def one(self):\n"
            "        with self._a:\n"
            "            self.two()\n"
            "    def two(self):\n"
            "        with self._a:\n"
            "            pass\n"
        ))
        assert "GL601" not in active_ids(res)

    def test_suppressed(self, tmp_path):
        # edges are reported at the INNER acquisition's `with` line
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class R:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "    def one(self):\n"
            "        with self._a:\n"
            "            with self._b:  "
            "# graftlint: disable=GL601 (fixture)\n"
            "                pass\n"
            "    def two(self):\n"
            "        with self._b:\n"
            "            with self._a:  "
            "# graftlint: disable=GL601 (fixture)\n"
            "                pass\n"
        ))
        assert "GL601" not in active_ids(res)
        assert "GL601" in all_ids(res)


class TestGL602BlockingUnderLock:
    def test_positive_sleep(self, tmp_path):
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class R:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            time.sleep(1.0)\n"
        ))
        assert "GL602" in active_ids(res)

    def test_positive_queue_get_no_timeout(self, tmp_path):
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class R:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._q = queue.Queue()\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            return self._q.get()\n"
        ))
        assert "GL602" in active_ids(res)

    def test_positive_thread_join(self, tmp_path):
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class R:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._t = threading.Thread(target=print)\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            self._t.join()\n"
        ))
        assert "GL602" in active_ids(res)

    def test_positive_event_wait(self, tmp_path):
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class R:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._evt = threading.Event()\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            self._evt.wait()\n"
        ))
        assert "GL602" in active_ids(res)

    def test_negative_queue_get_with_timeout(self, tmp_path):
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class R:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._q = queue.Queue()\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            return self._q.get(timeout=0.5)\n"
        ))
        assert "GL602" not in active_ids(res)

    def test_negative_queue_get_nonblocking(self, tmp_path):
        # get(False) / get(block=False) return immediately — the
        # standard non-blocking idiom must not fail the gate
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class R:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._q = queue.Queue()\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            return self._q.get(False)\n"
            "    def b(self):\n"
            "        with self._lock:\n"
            "            return self._q.get(block=False)\n"
        ))
        assert "GL602" not in active_ids(res)

    def test_negative_cond_wait_on_held_condition(self, tmp_path):
        # Condition.wait RELEASES the held condition — correct idiom
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class R:\n"
            "    def __init__(self):\n"
            "        self._cond = threading.Condition()\n"
            "    def a(self):\n"
            "        with self._cond:\n"
            "            self._cond.wait()\n"
        ))
        assert "GL602" not in active_ids(res)

    def test_positive_cond_wait_still_holding_other(self, tmp_path):
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class R:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._cond = threading.Condition()\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            with self._cond:\n"
            "                self._cond.wait()\n"
        ))
        assert "GL602" in active_ids(res)

    def test_negative_sleep_outside_lock(self, tmp_path):
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class R:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            x = 1\n"
            "        time.sleep(1.0)\n"
        ))
        assert "GL602" not in active_ids(res)

    def test_negative_str_join_is_not_blocking(self, tmp_path):
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class R:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.names = ['a']\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            return ', '.join(self.names)\n"
        ))
        assert "GL602" not in active_ids(res)

    def test_suppressed(self, tmp_path):
        res = lint_src(tmp_path, LOCKS_HEADER + (
            "class R:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            time.sleep(1.0)  "
            "# graftlint: disable=GL602 (fixture)\n"
        ))
        assert "GL602" not in active_ids(res)
        assert "GL602" in all_ids(res)


class TestParseErrors:
    def test_unparseable_file_is_reported(self, tmp_path):
        res = lint_src(tmp_path, "def broken(:\n")
        assert res.parse_errors, "torn file must be surfaced, not skipped"


class TestCLI:
    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, str(GRAFTLINT), *argv],
            capture_output=True, text=True, cwd=str(REPO),
        )

    def test_json_output_is_stable_and_parseable(self, tmp_path):
        (tmp_path / "m.py").write_text(JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.item()\n"
        ))
        r1 = self._run("--json", str(tmp_path))
        r2 = self._run("--json", str(tmp_path))
        assert r1.returncode == 1  # active finding -> gate fails
        assert r1.stdout == r2.stdout, "JSON output must be deterministic"
        doc = json.loads(r1.stdout)
        assert doc["graftlint"] == 1
        assert doc["summary"]["active"] == 1
        assert doc["rules"] == sorted(RULES_BY_ID)
        (f,) = [x for x in doc["findings"] if not x["suppressed"]]
        assert set(f) == {
            "path", "line", "rule", "name", "severity", "message",
            "hint", "suppressed",
        }
        assert f["rule"] == "GL101"
        assert f["severity"] == "error"
        assert f["line"] == 5

    def test_clean_tree_exits_zero(self, tmp_path):
        (tmp_path / "m.py").write_text("def ok():\n    return 1\n")
        r = self._run("--json", str(tmp_path))
        assert r.returncode == 0
        doc = json.loads(r.stdout)
        assert doc["summary"]["active"] == 0

    def test_findings_sorted(self, tmp_path):
        (tmp_path / "b.py").write_text(JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    print(x)\n"
            "    return x.item()\n"
        ))
        (tmp_path / "a.py").write_text(JIT_HEADER + (
            "@jax.jit\n"
            "def g(x):\n"
            "    return x.item()\n"
        ))
        doc = json.loads(self._run("--json", str(tmp_path)).stdout)
        keys = [(f["path"], f["line"], f["rule"]) for f in doc["findings"]]
        assert keys == sorted(keys)

    def test_list_rules(self):
        r = self._run("--list-rules")
        assert r.returncode == 0
        for rule in RULES:
            assert rule.id in r.stdout

    def test_no_paths_is_usage_error(self):
        assert self._run().returncode == 2

    def test_unknown_rule_is_usage_error(self, tmp_path):
        # a typoed --rules must not lint nothing and exit 0 (a
        # misconfigured CI gate would pass forever)
        (tmp_path / "m.py").write_text("x = 1\n")
        r = self._run("--rules", "GL999", str(tmp_path))
        assert r.returncode == 2
        assert "unknown rule" in r.stderr

    def test_nonexistent_path_is_usage_error(self, tmp_path):
        # same contract as unknown rules: a typoed/renamed path must
        # not scan zero files and exit 0
        r = self._run(str(tmp_path / "renamed_away"))
        assert r.returncode == 2
        assert "does not exist" in r.stderr

    def test_path_with_no_py_files_is_usage_error(self, tmp_path):
        (tmp_path / "README.txt").write_text("no python here\n")
        r = self._run(str(tmp_path))
        assert r.returncode == 2
        assert "no .py files" in r.stderr

    def test_non_py_file_arg_is_usage_error(self, tmp_path):
        target = tmp_path / "notes.txt"
        target.write_text("x = 1\n")
        r = self._run(str(target))
        assert r.returncode == 2
        assert "no .py files" in r.stderr

    def test_parse_error_fails_gate(self, tmp_path):
        (tmp_path / "broken.py").write_text("def broken(:\n")
        r = self._run("--json", str(tmp_path))
        assert r.returncode == 1
        doc = json.loads(r.stdout)
        assert len(doc["parse_errors"]) == 1
        assert doc["parse_errors"][0].endswith("broken.py")

    def test_list_rules_shows_all_families(self):
        r = self._run("--list-rules")
        assert r.returncode == 0
        for fam in ("GL101", "GL201", "GL301", "GL401", "GL402", "GL403",
                    "GL501", "GL502", "GL503", "GL504", "GL601", "GL602"):
            assert fam in r.stdout, f"{fam} missing from --list-rules"
        assert "[warning]" in r.stdout  # GL503's severity is surfaced

    def test_warning_severity_does_not_gate(self, tmp_path):
        (tmp_path / "m.py").write_text(
            "import jax\nimport jax.numpy as jnp\n"
            "from jax.experimental import pallas as pl\n"
            "from jax.experimental.pallas import tpu as pltpu\n"
            "def kern(x_ref, o_ref, acc_ref):\n"
            "    o_ref[...] = x_ref[...]\n"
            "def call(x, M):\n"
            "    return pl.pallas_call(\n"
            "        kern,\n"
            "        out_shape=jax.ShapeDtypeStruct((M, 128), jnp.float32),\n"
            "        scratch_shapes=[pltpu.VMEM((2048, 4096),\n"
            "                                   jnp.float32)],\n"
            "    )(x)\n"
        )
        r = self._run("--json", str(tmp_path))
        doc = json.loads(r.stdout)
        assert r.returncode == 0, "a lone GL503 warning must not gate"
        assert doc["summary"]["active"] == 1
        assert doc["summary"]["warnings"] == 1
        (f,) = doc["findings"]
        assert f["rule"] == "GL503" and f["severity"] == "warning"

    def test_vmem_budget_flag(self, tmp_path):
        (tmp_path / "m.py").write_text(
            "import jax\nimport jax.numpy as jnp\n"
            "from jax.experimental import pallas as pl\n"
            "from jax.experimental.pallas import tpu as pltpu\n"
            "def kern(x_ref, o_ref, acc_ref):\n"
            "    o_ref[...] = x_ref[...]\n"
            "def call(x, M):\n"
            "    return pl.pallas_call(\n"
            "        kern,\n"
            "        out_shape=jax.ShapeDtypeStruct((M, 128), jnp.float32),\n"
            "        scratch_shapes=[pltpu.VMEM((2048, 4096),\n"
            "                                   jnp.float32)],\n"
            "    )(x)\n"
        )
        doc = json.loads(
            self._run("--json", "--vmem-budget", "64", str(tmp_path)).stdout
        )
        assert doc["summary"]["active"] == 0
        doc = json.loads(
            self._run("--json", "--vmem-budget", "8", str(tmp_path)).stdout
        )
        assert doc["summary"]["active"] == 1


class TestSarifOutput:
    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, str(GRAFTLINT), *argv],
            capture_output=True, text=True, cwd=str(REPO),
        )

    def _fixture(self, tmp_path):
        (tmp_path / "m.py").write_text(JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    print(x)\n"
            "    return x.item()\n"
            "@jax.jit\n"
            "def g(x):\n"
            "    return x.tolist()  # graftlint: disable=GL101 (fixture)\n"
        ))

    def test_schema_and_determinism(self, tmp_path):
        self._fixture(tmp_path)
        r1 = self._run("--format", "sarif", str(tmp_path))
        r2 = self._run("--format", "sarif", str(tmp_path))
        assert r1.returncode == 1  # active findings still gate
        assert r1.stdout == r2.stdout, "SARIF must be deterministic"
        doc = json.loads(r1.stdout)
        assert doc["version"] == "2.1.0"
        assert "sarif" in doc["$schema"]
        (run,) = doc["runs"]
        driver = run["tool"]["driver"]
        assert driver["name"] == "graftlint"
        rule_ids = [r["id"] for r in driver["rules"]]
        assert rule_ids == sorted(rule_ids)
        from differential_transformer_replication_tpu.analysis import (
            RULES_BY_ID as _R,
        )
        assert set(rule_ids) == set(_R)
        for res in run["results"]:
            assert set(res) >= {"ruleId", "level", "message", "locations"}
            (loc,) = res["locations"]
            phys = loc["physicalLocation"]
            assert phys["artifactLocation"]["uri"].endswith("m.py")
            assert phys["region"]["startLine"] >= 1

    def test_suppressed_findings_carried_as_suppressions(self, tmp_path):
        self._fixture(tmp_path)
        doc = json.loads(
            self._run("--format", "sarif", str(tmp_path)).stdout
        )
        sup = [
            r for r in doc["runs"][0]["results"] if r.get("suppressions")
        ]
        assert len(sup) == 1
        assert sup[0]["suppressions"] == [{"kind": "inSource"}]

    def test_warning_level_mapped(self, tmp_path):
        (tmp_path / "m.py").write_text(
            "import jax\nimport jax.numpy as jnp\n"
            "from jax.experimental import pallas as pl\n"
            "from jax.experimental.pallas import tpu as pltpu\n"
            "def kern(x_ref, o_ref, acc_ref):\n"
            "    o_ref[...] = x_ref[...]\n"
            "def call(x, M):\n"
            "    return pl.pallas_call(\n"
            "        kern,\n"
            "        out_shape=jax.ShapeDtypeStruct((M, 128), jnp.float32),\n"
            "        scratch_shapes=[pltpu.VMEM((2048, 4096),\n"
            "                                   jnp.float32)],\n"
            "    )(x)\n"
        )
        r = self._run("--format", "sarif", str(tmp_path))
        assert r.returncode == 0
        doc = json.loads(r.stdout)
        (res,) = doc["runs"][0]["results"]
        assert res["ruleId"] == "GL503" and res["level"] == "warning"

    def test_json_conflict_is_usage_error(self, tmp_path):
        (tmp_path / "m.py").write_text("x = 1\n")
        r = self._run("--json", "--format", "sarif", str(tmp_path))
        assert r.returncode == 2


class TestChangedMode:
    def _run(self, *argv, cwd):
        return subprocess.run(
            [sys.executable, str(GRAFTLINT), *argv],
            capture_output=True, text=True, cwd=cwd,
        )

    def _git(self, cwd, *argv):
        return subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t", *argv],
            cwd=str(cwd), capture_output=True, text=True, check=True,
        )

    def _repo(self, tmp_path):
        self._git(tmp_path, "init", "-q")
        (tmp_path / "old_bad.py").write_text(JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.item()\n"
        ))
        self._git(tmp_path, "add", "old_bad.py")
        self._git(tmp_path, "commit", "-qm", "seed")
        return tmp_path

    def test_only_changed_files_reported(self, tmp_path):
        repo = self._repo(tmp_path)
        # a NEW untracked hazard file and an UNCHANGED committed one
        (repo / "new_bad.py").write_text(JIT_HEADER + (
            "@jax.jit\n"
            "def g(x):\n"
            "    print(x)\n"
            "    return x\n"
        ))
        r = self._run("--changed", "HEAD", "--json", ".", cwd=str(repo))
        doc = json.loads(r.stdout)
        assert r.returncode == 1
        assert doc["changed_vs"] == "HEAD"
        paths = {f["path"] for f in doc["findings"]}
        assert all(p.endswith("new_bad.py") for p in paths), paths
        # ...while the full run still sees both
        r_full = self._run("--json", ".", cwd=str(repo))
        full_paths = {
            f["path"] for f in json.loads(r_full.stdout)["findings"]
        }
        assert any(p.endswith("old_bad.py") for p in full_paths)

    def test_unchanged_tree_exits_zero(self, tmp_path):
        repo = self._repo(tmp_path)
        r = self._run("--changed", "HEAD", ".", cwd=str(repo))
        assert r.returncode == 0, r.stdout + r.stderr

    def test_call_graph_spans_whole_tree(self, tmp_path):
        # the hazard lives in an UNTOUCHED helper module; the CHANGED
        # file jits a function that calls it. Cross-module reachability
        # must survive the file filter: the finding lands in the helper
        # (unchanged -> filtered out, exit 0), but the jit-region count
        # proves the whole tree was analyzed, and editing the helper
        # itself surfaces it.
        repo = self._repo(tmp_path)
        (repo / "helper.py").write_text(
            "def deep(x):\n"
            "    return x.item()\n"
        )
        self._git(repo, "add", "helper.py")
        self._git(repo, "commit", "-qm", "helper")
        (repo / "entry.py").write_text(JIT_HEADER + (
            "from helper import deep\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return deep(x)\n"
        ))
        r = self._run("--changed", "HEAD", "--json", ".", cwd=str(repo))
        doc = json.loads(r.stdout)
        # finding is attributed to helper.py (unchanged) -> filtered;
        # nothing in entry.py itself
        assert all(
            not f["path"].endswith("entry.py") for f in doc["findings"]
        )
        # whole-tree analysis really happened (files_scanned is global)
        assert doc["files_scanned"] == 3
        # now touch the helper too: the finding surfaces in changed mode
        (repo / "helper.py").write_text(
            "def deep(x):\n"
            "    return x.item()\n"
            "\n"
            "def deep2(x):\n"
            "    return x\n"
        )
        r2 = self._run("--changed", "HEAD", "--json", ".", cwd=str(repo))
        doc2 = json.loads(r2.stdout)
        assert any(
            f["path"].endswith("helper.py") and f["rule"] == "GL101"
            for f in doc2["findings"]
        )
        assert r2.returncode == 1

    def test_findings_survive_symlinked_path(self, tmp_path):
        # git reports the PHYSICAL toplevel; reaching the repo through
        # a symlink must not silently filter every finding (gate would
        # pass on real hazards)
        (tmp_path / "real").mkdir()
        repo = self._repo(tmp_path / "real")
        (repo / "new_bad.py").write_text(JIT_HEADER + (
            "@jax.jit\n"
            "def g(x):\n"
            "    return x.item()\n"
        ))
        link = tmp_path / "link"
        link.symlink_to(repo)
        r = self._run("--changed", "HEAD", "--json", ".", cwd=str(link))
        doc = json.loads(r.stdout)
        assert r.returncode == 1
        assert any(
            f["path"].endswith("new_bad.py") for f in doc["findings"]
        )

    def test_bad_ref_is_usage_error(self, tmp_path):
        repo = self._repo(tmp_path)
        r = self._run("--changed", "no-such-ref", ".", cwd=str(repo))
        assert r.returncode == 2
        assert "git diff" in r.stderr

    def test_outside_git_is_usage_error(self, tmp_path):
        (tmp_path / "m.py").write_text("x = 1\n")
        import os as _os
        env_dir = tmp_path / "isolated"
        env_dir.mkdir()
        (env_dir / "m.py").write_text("x = 1\n")
        r = subprocess.run(
            [sys.executable, str(GRAFTLINT), "--changed", "HEAD", "m.py"],
            capture_output=True, text=True, cwd=str(env_dir),
            env={**_os.environ, "GIT_CEILING_DIRECTORIES": str(tmp_path)},
        )
        assert r.returncode == 2


class TestGL301CoversPagePool:
    """Mutation test for the paged-KV pool's lock discipline
    (serving/pages.py): PagePool is a lock-owning class shared between
    the engine thread and /health readers, so GL301 is the machine
    check that its refcount/accounting writes stay under
    ``self._lock``. Planting exactly that bug — an admission-side
    counter write hoisted OUT of the lock — in the real module source
    MUST fire; the unmutated module must stay clean."""

    PAGES = (
        REPO / "differential_transformer_replication_tpu" / "serving"
        / "pages.py"
    )
    ANCHOR = (
        "        with self._lock:\n"
        "            self._clock += 1\n"
        "            for n in self._slot_nodes[slot]:"
    )

    def _copy(self, tmp_path, src):
        # keep the serving/ path component: GL301 is a serving-dir rule
        path = tmp_path / "serving" / "pages.py"
        path.parent.mkdir(parents=True)
        path.write_text(src)
        return path

    def test_unmutated_pages_is_lock_clean(self, tmp_path):
        path = self._copy(tmp_path, self.PAGES.read_text())
        result = lint_paths([str(path)],
                            rules=["GL301", "GL601", "GL602"])
        assert active_ids(result) == []

    def test_planted_off_lock_refcount_write_fires(self, tmp_path):
        src = self.PAGES.read_text()
        assert self.ANCHOR in src, (
            "mutation anchor vanished — PagePool.release's lock block "
            "moved; update the anchor so this mutation test keeps "
            "guarding it"
        )
        mutated = src.replace(
            self.ANCHOR,
            "        self._hits += 1  # planted: off-lock write\n"
            + self.ANCHOR,
        )
        path = self._copy(tmp_path, mutated)
        result = lint_paths([str(path)], rules=["GL301"])
        assert active_ids(result) == ["GL301"]
        (finding,) = result.active
        assert "_hits" in finding.message

    def test_planted_write_under_lock_stays_clean(self, tmp_path):
        # negative control: the same write INSIDE the lock block is the
        # correct idiom and must not fire
        src = self.PAGES.read_text()
        mutated = src.replace(
            self.ANCHOR,
            "        with self._lock:\n"
            "            self._hits += 0  # inside the lock: fine\n"
            "            self._clock += 1\n"
            "            for n in self._slot_nodes[slot]:",
        )
        path = self._copy(tmp_path, mutated)
        result = lint_paths([str(path)], rules=["GL301"])
        assert active_ids(result) == []


class TestGL602CoversResilienceThreads:
    """Mutation test for the heartbeat/watchdog threads' lock usage:
    GL602 is the machine check that those daemon threads never block
    under a held lock (a heartbeat monitor sleeping under its lock
    would stall the publisher — and with it the liveness signal every
    peer depends on). Planting exactly that bug in the real module
    source MUST fire; the unmutated module must stay clean."""

    HEARTBEAT = (
        REPO / "differential_transformer_replication_tpu" / "parallel"
        / "heartbeat.py"
    )
    ANCHOR = (
        "with self._lock:\n"
        "            for p in list(self._last_change):"
    )

    def test_unmutated_heartbeat_is_gl602_clean(self, tmp_path):
        src = self.HEARTBEAT.read_text()
        (tmp_path / "heartbeat.py").write_text(src)
        result = lint_paths([str(tmp_path / "heartbeat.py")],
                            rules=["GL601", "GL602"])
        assert active_ids(result) == []

    def test_planted_blocking_call_under_lock_fires(self, tmp_path):
        src = self.HEARTBEAT.read_text()
        assert self.ANCHOR in src, (
            "mutation anchor vanished — heartbeat.py's monitor lock "
            "block moved; update the anchor so this mutation test "
            "keeps guarding it"
        )
        mutated = src.replace(
            self.ANCHOR,
            "with self._lock:\n"
            "            time.sleep(0.5)  # planted: blocking under lock\n"
            "            for p in list(self._last_change):",
        )
        (tmp_path / "heartbeat.py").write_text(mutated)
        result = lint_paths([str(tmp_path / "heartbeat.py")],
                            rules=["GL602"])
        assert active_ids(result) == ["GL602"]
        (finding,) = result.active
        assert "time.sleep" in finding.message
        assert "Heartbeat._lock" in finding.message

    def test_planted_lockless_sleep_stays_clean(self, tmp_path):
        """The negative control: the same sleep OUTSIDE the lock is the
        correct pacing idiom and must not fire (otherwise the clean
        gate would force suppressions onto legitimate code)."""
        src = self.HEARTBEAT.read_text()
        mutated = src.replace(
            self.ANCHOR,
            "time.sleep(0.0)  # outside the lock: fine\n"
            "        " + self.ANCHOR,
        )
        (tmp_path / "heartbeat.py").write_text(mutated)
        result = lint_paths([str(tmp_path / "heartbeat.py")],
                            rules=["GL602"])
        assert active_ids(result) == []
