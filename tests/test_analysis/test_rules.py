"""graftlint rule-engine tests: per-rule positive + negative +
suppressed fixtures, jit-region discovery (decorators, call sites,
maker idiom, cross-module reachability through re-exports), and the
CLI's machine-parseable ``--json`` contract.

Every rule in analysis/rules.py has a POSITIVE fixture here proving it
fires — the acceptance contract: a rule that cannot fire is dead
weight, and a rule that fires on clean idioms would poison the
clean-tree gate (tests/test_lint_clean.py)."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from differential_transformer_replication_tpu.analysis import (
    RULES,
    RULES_BY_ID,
    lint_paths,
)

REPO = Path(__file__).resolve().parents[2]
GRAFTLINT = REPO / "tools" / "graftlint.py"


def lint_src(tmp_path, src, filename="mod.py", rules=None):
    """Write one fixture module and lint the directory; returns the
    list of ACTIVE finding rule ids (sorted, duplicates kept)."""
    path = tmp_path / filename
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(src)
    result = lint_paths([str(tmp_path)], rules=rules)
    return result


def active_ids(result):
    return sorted(f.rule for f in result.active)


def all_ids(result):
    return sorted(f.rule for f in result.findings)


JIT_HEADER = "import jax\nimport jax.numpy as jnp\n"


class TestRuleCatalog:
    def test_at_least_eight_distinct_rules(self):
        assert len(RULES) >= 8
        assert len({r.id for r in RULES}) == len(RULES)

    def test_every_rule_documented(self):
        for r in RULES:
            assert r.summary and r.hint, f"{r.id} missing docs"


class TestGL101HostSync:
    def test_positive_item(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    v = jnp.sum(x)\n"
            "    return v.item()\n"
        ))
        assert "GL101" in active_ids(res)

    def test_positive_device_get(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    return jax.device_get(x)\n"
        ))
        assert "GL101" in active_ids(res)

    def test_positive_np_asarray_on_traced(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "import numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    s = jnp.sum(x)\n"
            "    return np.asarray(s)\n"
        ))
        assert "GL101" in active_ids(res)

    def test_negative_outside_jit(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def host(x):\n"
            "    return x.item()\n"
        ))
        assert "GL101" not in active_ids(res)

    def test_negative_np_asarray_on_host_value(self, tmp_path):
        # np.asarray of an untraced (host) value in a jit region is a
        # trace-time constant, not a sync
        res = lint_src(tmp_path, JIT_HEADER + (
            "import numpy as np\n"
            "@jax.jit\n"
            "def f(x, lens):\n"
            "    table = np.asarray([1, 2, 3])\n"
            "    return x + table\n"
        ))
        assert "GL101" not in active_ids(res)

    def test_suppressed(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    v = jnp.sum(x)\n"
            "    return v.item()  # graftlint: disable=GL101\n"
        ))
        assert "GL101" not in active_ids(res)
        assert "GL101" in all_ids(res)  # reported, flagged suppressed


class TestGL102HostCast:
    def test_positive(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    s = jnp.sum(x)\n"
            "    return float(s)\n"
        ))
        assert "GL102" in active_ids(res)

    def test_negative_static_cast(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x, cfg_scale):\n"
            "    n = float(x.shape[0])\n"  # shapes are static
            "    return x * n\n"
        ))
        assert "GL102" not in active_ids(res)


class TestGL103ImpureCall:
    def test_positive_time(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "import time\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x * time.time()\n"
        ))
        assert "GL103" in active_ids(res)

    def test_positive_np_random(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "import numpy as np\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x + np.random.rand()\n"
        ))
        assert "GL103" in active_ids(res)

    def test_positive_print(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    print(x)\n"
            "    return x\n"
        ))
        assert "GL103" in active_ids(res)

    def test_negative_jax_random(self, tmp_path):
        # `from jax import random; random.normal(...)` is pure — the
        # alias must resolve to jax.random, not stdlib random
        res = lint_src(tmp_path, (
            "import jax\nfrom jax import random\n"
            "@jax.jit\n"
            "def f(key, x):\n"
            "    return x + random.normal(key, x.shape)\n"
        ))
        assert "GL103" not in active_ids(res)

    def test_negative_host_print(self, tmp_path):
        res = lint_src(tmp_path, (
            "def host():\n"
            "    print('hello')\n"
        ))
        assert "GL103" not in active_ids(res)


class TestGL104TracedBranch:
    def test_positive_if(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    s = jnp.sum(x)\n"
            "    if s > 0:\n"
            "        return x\n"
            "    return -x\n"
        ))
        assert "GL104" in active_ids(res)

    def test_positive_while(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    s = jnp.max(x)\n"
            "    while s > 0:\n"
            "        s = s - 1\n"
            "    return s\n"
        ))
        assert "GL104" in active_ids(res)

    def test_negative_static_config_branch(self, tmp_path):
        # branching on config/static values is the normal jit idiom
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x, n_micro=1):\n"
            "    if x.shape[0] == 1:\n"
            "        return x\n"
            "    return x * 2\n"
        ))
        assert "GL104" not in active_ids(res)

    def test_taint_propagates_through_arithmetic(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    s = jnp.sum(x)\n"
            "    t = s * 2 + 1\n"
            "    if t > 3:\n"
            "        return x\n"
            "    return -x\n"
        ))
        assert "GL104" in active_ids(res)

    def test_shape_access_strips_taint(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    h = jnp.reshape(x, (-1,))\n"
            "    if h.shape[0] > 4:\n"
            "        return h\n"
            "    return -h\n"
        ))
        assert "GL104" not in active_ids(res)

    def test_positive_branch_on_bare_parameter(self, tmp_path):
        # a jit root's params ARE the traced values — the canonical
        # hazard form must fire without any jnp call seeding taint
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def step_fn(x, y):\n"
            "    if x > 0:\n"
            "        return float(x)\n"
            "    return y\n"
        ))
        assert "GL104" in active_ids(res)
        assert "GL102" in active_ids(res)

    def test_positive_scan_body_param_while(self, tmp_path):
        # call-site roots (lax.scan body) get param seeding too
        res = lint_src(tmp_path, JIT_HEADER + (
            "def body(carry, t):\n"
            "    s = carry + t\n"
            "    while s > 0:\n"
            "        s = s - 1\n"
            "    return s, s\n"
            "out = jax.lax.scan(body, 0, None)\n"
        ))
        assert "GL104" in active_ids(res)

    def test_negative_attr_read_on_parameter(self, tmp_path):
        # config objects arrive as params; attribute reads on a bare
        # param stay static (if cfg.dropout > 0 is the normal idiom)
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x, cfg):\n"
            "    if cfg.dropout > 0:\n"
            "        return x * cfg.scale\n"
            "    return x\n"
        ))
        assert "GL104" not in active_ids(res)

    def test_negative_is_none_on_parameter(self, tmp_path):
        # identity tests never boolify a tracer
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x, mask):\n"
            "    if mask is not None:\n"
            "        x = x + mask\n"
            "    s = jnp.sum(x)\n"
            "    if s is None:\n"
            "        return x\n"
            "    return s\n"
        ))
        assert "GL104" not in active_ids(res)

    def test_negative_static_argnums_param(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "from functools import partial\n"
            "@partial(jax.jit, static_argnums=(1,))\n"
            "def f(x, n):\n"
            "    if n > 4:\n"
            "        return x * n\n"
            "    return x\n"
        ))
        assert "GL104" not in active_ids(res)

    def test_negative_static_argnames_call_site(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def f(x, n):\n"
            "    if n > 4:\n"
            "        return x * n\n"
            "    return x\n"
            "g = jax.jit(f, static_argnames=('n',))\n"
        ))
        assert "GL104" not in active_ids(res)

    def test_negative_helper_params_not_seeded(self, tmp_path):
        # transitively-reached helpers take host-static params (chunk
        # sizes, positions); only ROOT params are seeded
        res = lint_src(tmp_path, JIT_HEADER + (
            "def helper(x, chunk):\n"
            "    if chunk > 4:\n"
            "        return x[:chunk]\n"
            "    return x\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return helper(x, 8)\n"
        ))
        assert "GL104" not in active_ids(res)

    def test_param_rebound_to_host_value_drops_seed(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x, w):\n"
            "    w = 4\n"
            "    if w > 2:\n"
            "        return x * w\n"
            "    return x\n"
        ))
        assert "GL104" not in active_ids(res)


class TestGL105FString:
    def test_positive(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    s = jnp.max(x)\n"
            "    label = f'max={s}'\n"
            "    return x\n"
        ))
        assert "GL105" in active_ids(res)

    def test_negative_in_raise(self, tmp_path):
        # error messages at trace time run on static data — exempt
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    if x.shape[0] == 0:\n"
            "        raise ValueError(f'empty input {x.shape}')\n"
            "    return x\n"
        ))
        assert "GL105" not in active_ids(res)

    def test_negative_in_assert(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x, k):\n"
            "    assert x.shape[0] == k, f'bad shape {x.shape}'\n"
            "    return x\n"
        ))
        assert "GL105" not in active_ids(res)


class TestGL106SetIteration:
    def test_positive(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(params):\n"
            "    total = 0.0\n"
            "    for k in {'wq', 'wk', 'wv'}:\n"
            "        total = total + jnp.sum(params[k])\n"
            "    return total\n"
        ))
        assert "GL106" in active_ids(res)

    def test_positive_comprehension(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(params):\n"
            "    vals = [params[k] for k in {'a', 'b'}]\n"
            "    return vals[0]\n"
        ))
        assert "GL106" in active_ids(res)

    def test_negative_sorted_iteration(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(params):\n"
            "    total = 0.0\n"
            "    for k in ('wq', 'wk', 'wv'):\n"
            "        total = total + jnp.sum(params[k])\n"
            "    return total\n"
        ))
        assert "GL106" not in active_ids(res)


class TestGL107GlobalState:
    def test_positive(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "_cache = None\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    global _cache\n"
            "    _cache = x\n"
            "    return x\n"
        ))
        assert "GL107" in active_ids(res)

    def test_negative_host_global(self, tmp_path):
        res = lint_src(tmp_path, (
            "_cache = None\n"
            "def host(x):\n"
            "    global _cache\n"
            "    _cache = x\n"
        ))
        assert "GL107" not in active_ids(res)


class TestGL201MissingDonate:
    def test_positive_call_form(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def train_step(state, batch):\n"
            "    return state\n"
            "jitted = jax.jit(train_step)\n"
        ))
        assert "GL201" in active_ids(res)

    def test_positive_decorator_form(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def decode_step(pool, tokens):\n"
            "    return pool\n"
        ))
        assert "GL201" in active_ids(res)

    def test_negative_with_donate(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "from functools import partial\n"
            "def train_step(state, batch):\n"
            "    return state\n"
            "jitted = jax.jit(train_step, donate_argnums=(0,))\n"
            "@partial(jax.jit, donate_argnums=(0,))\n"
            "def update_step(state):\n"
            "    return state\n"
        ))
        assert "GL201" not in active_ids(res)

    def test_negative_eval_exempt(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def eval_step(params, x):\n"
            "    return params\n"
        ))
        assert "GL201" not in active_ids(res)

    def test_negative_maker_call_with_donate(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def make_step_fn(cfg):\n"
            "    def step(state, batch):\n"
            "        return state\n"
            "    return step\n"
            "jitted = jax.jit(make_step_fn(None), donate_argnums=(0,))\n"
        ))
        assert "GL201" not in active_ids(res)


class TestGL202SyncInStepLoop:
    def test_positive(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def train(step, state, batch):\n"
            "    for i in range(100):\n"
            "        state, metrics = step(state, batch)\n"
            "        loss = float(metrics['loss'])\n"
            "    return loss\n"
        ))
        assert "GL202" in active_ids(res)

    def test_positive_device_get(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def train(train_step, state, batch):\n"
            "    while True:\n"
            "        state, metrics = train_step(state, batch)\n"
            "        m = jax.device_get(metrics)\n"
        ))
        assert "GL202" in active_ids(res)

    def test_negative_outside_loop(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def train(step, state, batch):\n"
            "    for i in range(100):\n"
            "        state, metrics = step(state, batch)\n"
            "    return float(metrics['loss'])\n"
        ))
        assert "GL202" not in active_ids(res)

    def test_negative_loop_without_step(self, tmp_path):
        res = lint_src(tmp_path, (
            "def tally(xs):\n"
            "    total = 0.0\n"
            "    for x in xs:\n"
            "        total += float(x)\n"
            "    return total\n"
        ))
        assert "GL202" not in active_ids(res)

    def test_suppressed_with_trailing_why(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def train(step, state, batch):\n"
            "    for i in range(100):\n"
            "        state, metrics = step(state, batch)\n"
            "        if i % 50 == 0:\n"
            "            loss = float(metrics['loss'])  "
            "# graftlint: disable=GL202 (log-boundary sync)\n"
        ))
        assert "GL202" not in active_ids(res)
        assert "GL202" in all_ids(res)


class TestGL301LockDiscipline:
    POS = (
        "import threading\n"
        "class Runner:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.count = 0\n"
        "    def bump(self):\n"
        "        self.count += 1\n"
        "    def read(self):\n"
        "        with self._lock:\n"
        "            return self.count\n"
    )

    def test_positive_in_serving_dir(self, tmp_path):
        res = lint_src(tmp_path, self.POS, filename="serving/runner.py")
        assert "GL301" in active_ids(res)

    def test_negative_outside_serving(self, tmp_path):
        res = lint_src(tmp_path, self.POS, filename="train/runner.py")
        assert "GL301" not in active_ids(res)

    def test_positive_direct_file_invocation(self, tmp_path):
        # spot-linting ONE serving file must apply the same rules as
        # linting the directory (file args keep one parent component)
        path = tmp_path / "serving" / "runner.py"
        path.parent.mkdir(parents=True)
        path.write_text(self.POS)
        res = lint_paths([str(path)])
        assert "GL301" in active_ids(res)

    def test_negative_checkout_under_serving_parent(self, tmp_path):
        # a repo cloned at /somewhere/serving/repo must NOT have the
        # serving-only rule applied to its whole tree — membership is
        # lint-root-relative, never absolute
        root = tmp_path / "serving" / "repo"
        (root / "train").mkdir(parents=True)
        (root / "train" / "runner.py").write_text(self.POS)
        res = lint_paths([str(root)])
        assert "GL301" not in active_ids(res)

    def test_negative_guarded_write(self, tmp_path):
        res = lint_src(tmp_path, (
            "import threading\n"
            "class Runner:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.count = 0\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self.count += 1\n"
            "    def read(self):\n"
            "        with self._lock:\n"
            "            return self.count\n"
        ), filename="serving/runner.py")
        assert "GL301" not in active_ids(res)

    def test_negative_lockless_class_exempt(self, tmp_path):
        # classes that own no lock are single-threaded by design here
        res = lint_src(tmp_path, (
            "class Plain:\n"
            "    def __init__(self):\n"
            "        self.count = 0\n"
            "    def bump(self):\n"
            "        self.count += 1\n"
        ), filename="serving/plain.py")
        assert "GL301" not in active_ids(res)

    def test_threadsafe_alias_suppression(self, tmp_path):
        res = lint_src(tmp_path, (
            "import threading\n"
            "class Runner:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self.count = 0\n"
            "    def bump(self):\n"
            "        self.count += 1  # graftlint: threadsafe (GIL pub)\n"
            "    def read(self):\n"
            "        with self._lock:\n"
            "            return self.count\n"
        ), filename="serving/runner.py")
        assert "GL301" not in active_ids(res)
        assert "GL301" in all_ids(res)


class TestJitRegionDiscovery:
    def test_call_site_transform_marks_root(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def body(x):\n"
            "    return x.item()\n"
            "jitted = jax.jit(body)\n"
        ))
        assert "GL101" in active_ids(res)

    def test_lax_scan_body_is_jit_region(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "from jax import lax\n"
            "def outer(xs):\n"
            "    def body(carry, x):\n"
            "        return carry, x.item()\n"
            "    return lax.scan(body, 0.0, xs)\n"
        ))
        assert "GL101" in active_ids(res)

    def test_maker_idiom_marks_returned_fn(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def make_step(cfg):\n"
            "    def step(state, batch):\n"
            "        s = jnp.sum(batch)\n"
            "        return state, float(s)\n"
            "    return step\n"
            "jitted = jax.jit(make_step(None), donate_argnums=(0,))\n"
        ))
        assert "GL102" in active_ids(res)

    def test_callee_reached_through_call_graph(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def helper(x):\n"
            "    return x.item()\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return helper(x)\n"
        ))
        assert "GL101" in active_ids(res)

    def test_cross_module_reachability(self, tmp_path):
        (tmp_path / "impl.py").write_text(
            "def deep_helper(x):\n"
            "    return x.item()\n"
        )
        res = lint_src(tmp_path, (
            "import jax\n"
            "from impl import deep_helper\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return deep_helper(x)\n"
        ), filename="main.py")
        assert "GL101" in active_ids(res)
        # the finding lands in the CALLEE's file
        f = next(x for x in res.active if x.rule == "GL101")
        assert f.path.endswith("impl.py")

    def test_unreached_helper_is_host_code(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "def helper(x):\n"
            "    return x.item()\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x * 2\n"
        ))
        assert "GL101" not in active_ids(res)


class TestSuppressionSyntax:
    def test_disable_file(self, tmp_path):
        res = lint_src(tmp_path, (
            "# graftlint: disable-file=GL101\n"
        ) + JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.item()\n"
        ))
        assert "GL101" not in active_ids(res)
        assert "GL101" in all_ids(res)

    def test_disable_file_all(self, tmp_path):
        res = lint_src(tmp_path, (
            "# graftlint: disable-file\n"
        ) + JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    print(x)\n"
            "    return x.item()\n"
        ))
        assert not active_ids(res)

    def test_rule_name_token(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.item()  # graftlint: disable=host-sync-in-jit\n"
        ))
        assert "GL101" not in active_ids(res)

    def test_unknown_rule_token_is_inert(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.item()  # graftlint: disable=GL999\n"
        ))
        assert "GL101" in active_ids(res)

    def test_multiline_statement_suppressed_from_first_line(self, tmp_path):
        res = lint_src(tmp_path, JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    v = jax.device_get(  # graftlint: disable=GL101\n"
            "        x\n"
            "    )\n"
            "    return v\n"
        ))
        assert "GL101" not in active_ids(res)


class TestRuleFilter:
    def test_rules_option_limits_scope(self, tmp_path):
        src = JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    print(x)\n"
            "    return x.item()\n"
        )
        res = lint_src(tmp_path, src, rules=["GL103"])
        assert "GL103" in active_ids(res)
        assert "GL101" not in active_ids(res)


class TestSameBasenameArgs:
    def test_both_colliding_files_are_linted(self, tmp_path):
        # `graftlint a/util.py b/util.py` must scan BOTH (the old
        # last-writer-wins keying made the exit code order-dependent)
        bad = JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.item()\n"
        )
        clean = "def ok():\n    return 1\n"
        (tmp_path / "a").mkdir()
        (tmp_path / "b").mkdir()
        (tmp_path / "a" / "util.py").write_text(bad)
        (tmp_path / "b" / "util.py").write_text(clean)
        for order in (
            [tmp_path / "a" / "util.py", tmp_path / "b" / "util.py"],
            [tmp_path / "b" / "util.py", tmp_path / "a" / "util.py"],
        ):
            res = lint_paths([str(p) for p in order])
            assert res.files_scanned == 2
            assert "GL101" in active_ids(res), order

    def test_colliding_files_keep_their_own_suppression_spans(self, tmp_path):
        # both args display as serving/x.py; the statement-span cache
        # must stay per-FILE or one file's multi-line suppression is
        # checked against the other's statement extents
        plain = JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.item()\n"
        )
        suppressed = JIT_HEADER + (
            "@jax.jit\n"
            "def g(y):\n"
            "    v = (\n"
            "        y.item()\n"
            "    )  # graftlint: disable=GL101 (fixture)\n"
            "    return v\n"
        )
        (tmp_path / "a" / "serving").mkdir(parents=True)
        (tmp_path / "b" / "serving").mkdir(parents=True)
        (tmp_path / "a" / "serving" / "x.py").write_text(plain)
        (tmp_path / "b" / "serving" / "x.py").write_text(suppressed)
        res = lint_paths([
            str(tmp_path / "a" / "serving" / "x.py"),
            str(tmp_path / "b" / "serving" / "x.py"),
        ])
        gl101 = [f for f in res.findings if f.rule == "GL101"]
        assert [f.suppressed for f in gl101] == [False, True]


class TestParseErrors:
    def test_unparseable_file_is_reported(self, tmp_path):
        res = lint_src(tmp_path, "def broken(:\n")
        assert res.parse_errors, "torn file must be surfaced, not skipped"


class TestCLI:
    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, str(GRAFTLINT), *argv],
            capture_output=True, text=True, cwd=str(REPO),
        )

    def test_json_output_is_stable_and_parseable(self, tmp_path):
        (tmp_path / "m.py").write_text(JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.item()\n"
        ))
        r1 = self._run("--json", str(tmp_path))
        r2 = self._run("--json", str(tmp_path))
        assert r1.returncode == 1  # active finding -> gate fails
        assert r1.stdout == r2.stdout, "JSON output must be deterministic"
        doc = json.loads(r1.stdout)
        assert doc["graftlint"] == 1
        assert doc["summary"]["active"] == 1
        assert doc["rules"] == sorted(RULES_BY_ID)
        (f,) = [x for x in doc["findings"] if not x["suppressed"]]
        assert set(f) == {
            "path", "line", "rule", "name", "message", "hint", "suppressed"
        }
        assert f["rule"] == "GL101"
        assert f["line"] == 5

    def test_clean_tree_exits_zero(self, tmp_path):
        (tmp_path / "m.py").write_text("def ok():\n    return 1\n")
        r = self._run("--json", str(tmp_path))
        assert r.returncode == 0
        doc = json.loads(r.stdout)
        assert doc["summary"]["active"] == 0

    def test_findings_sorted(self, tmp_path):
        (tmp_path / "b.py").write_text(JIT_HEADER + (
            "@jax.jit\n"
            "def f(x):\n"
            "    print(x)\n"
            "    return x.item()\n"
        ))
        (tmp_path / "a.py").write_text(JIT_HEADER + (
            "@jax.jit\n"
            "def g(x):\n"
            "    return x.item()\n"
        ))
        doc = json.loads(self._run("--json", str(tmp_path)).stdout)
        keys = [(f["path"], f["line"], f["rule"]) for f in doc["findings"]]
        assert keys == sorted(keys)

    def test_list_rules(self):
        r = self._run("--list-rules")
        assert r.returncode == 0
        for rule in RULES:
            assert rule.id in r.stdout

    def test_no_paths_is_usage_error(self):
        assert self._run().returncode == 2

    def test_unknown_rule_is_usage_error(self, tmp_path):
        # a typoed --rules must not lint nothing and exit 0 (a
        # misconfigured CI gate would pass forever)
        (tmp_path / "m.py").write_text("x = 1\n")
        r = self._run("--rules", "GL999", str(tmp_path))
        assert r.returncode == 2
        assert "unknown rule" in r.stderr

    def test_nonexistent_path_is_usage_error(self, tmp_path):
        # same contract as unknown rules: a typoed/renamed path must
        # not scan zero files and exit 0
        r = self._run(str(tmp_path / "renamed_away"))
        assert r.returncode == 2
        assert "does not exist" in r.stderr

    def test_path_with_no_py_files_is_usage_error(self, tmp_path):
        (tmp_path / "README.txt").write_text("no python here\n")
        r = self._run(str(tmp_path))
        assert r.returncode == 2
        assert "no .py files" in r.stderr

    def test_non_py_file_arg_is_usage_error(self, tmp_path):
        target = tmp_path / "notes.txt"
        target.write_text("x = 1\n")
        r = self._run(str(target))
        assert r.returncode == 2
        assert "no .py files" in r.stderr

    def test_parse_error_fails_gate(self, tmp_path):
        (tmp_path / "broken.py").write_text("def broken(:\n")
        r = self._run("--json", str(tmp_path))
        assert r.returncode == 1
        doc = json.loads(r.stdout)
        assert len(doc["parse_errors"]) == 1
        assert doc["parse_errors"][0].endswith("broken.py")
