"""Durable-checkpoint tests: integrity manifests, atomic+fsynced
writes, `step-*` rotation with retention GC, the async writer thread,
verified resume (`--resume-from auto` + supervisor), ckpt_doctor, and
the SIGKILL-mid-async-save chaos test.

Tiering: unit and single-run tests are quick (tier-1); the chaos test
spawns real train.py subprocesses under the supervisor and is ``slow``.
The async-writer concurrency test and the trainer compile-count pin are
the acceptance checks that checkpoint I/O never blocks (or retraces)
the train loop.
"""

import hashlib
import importlib.util
import json
import os
import subprocess
import sys
import threading
import time

import jax
import pytest

from differential_transformer_replication_tpu.config import ModelConfig, TrainConfig
from differential_transformer_replication_tpu.train import (
    AsyncCheckpointWriter,
    CheckpointError,
    create_train_state,
    load_checkpoint,
    resolve_resume_auto,
    save_checkpoint,
    save_step_checkpoint,
    train,
    verify_checkpoint,
)
from differential_transformer_replication_tpu.train import ckpt_writer as cw
from differential_transformer_replication_tpu.utils import faults

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")
SUPERVISOR = os.path.join(TOOLS, "train_supervisor.py")
DOCTOR = os.path.join(TOOLS, "ckpt_doctor.py")
TRAIN_PY = os.path.join(os.path.dirname(__file__), "..", "train.py")

TINY_MODEL = dict(vocab_size=256, n_embd=32, n_head=2, n_layer=2,
                  block_size=16, dropout=0.0, compute_dtype="float32")


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def tiny_cfg(tmp_path, **kw):
    defaults = dict(
        vocab_size=256,
        dataset="synthetic",
        num_train_samples=200,
        micro_batch_size=4,
        grad_acc_steps=1,
        max_iters=20,
        eval_interval=10,
        eval_iters=2,
        log_interval=5,
        learning_rate=3e-3,
        min_lr=3e-4,
        warmup_iters=5,
        control_head_multiplier=1,
        tokenizer_dir=str(tmp_path / "tokenizer"),
        checkpoint_path=str(tmp_path / "ckpt"),
        last_checkpoint_path=str(tmp_path / "last_ckpt"),
        metrics_path=str(tmp_path / "metrics.jsonl"),
        seed=7,
    )
    return TrainConfig(
        model=ModelConfig(model=kw.pop("model", "diff"), **TINY_MODEL),
        **{**defaults, **kw},
    )


def step_cfg(**kw):
    return TrainConfig(
        model=ModelConfig(model="control", **{**TINY_MODEL, "vocab_size": 31}),
        vocab_size=31, learning_rate=1e-2, warmup_iters=2, max_iters=100,
        control_head_multiplier=1, **kw,
    )


def _flip_byte(path, offset=None):
    data = bytearray(open(path, "rb").read())
    i = (len(data) // 2) if offset is None else offset
    data[i] ^= 0xFF
    open(path, "wb").write(bytes(data))


def _mk_raw_ckpt(root, step, certify=True, payload=b"fake-state-bytes"):
    """A minimal (non-flax) certified checkpoint dir — enough for the
    manifest/GC/resolution machinery, which never deserializes."""
    path = os.path.join(root, cw.step_dir_name(step))
    os.makedirs(path, exist_ok=True)
    cw.atomic_write(os.path.join(path, "state.msgpack"), payload + b"%d" % step)
    cw.atomic_write(
        os.path.join(path, "meta.json"),
        json.dumps({"iter_num": step, "best_val_loss": 1.0}).encode(),
    )
    if certify:
        cw.write_manifest(path, step=step)
    return path


class TestAtomicWrite:
    def test_new_durability_fault_points_parse(self):
        faults.arm("ckpt_fsync,ckpt_manifest@2,ckpt_gc,ckpt_hang@3")
        assert faults.armed()

    def test_ckpt_write_fault_keeps_old_content(self, tmp_path):
        dest = str(tmp_path / "f")
        cw.atomic_write(dest, b"old")
        faults.arm("ckpt_write")
        with pytest.raises(faults.FaultInjected):
            cw.atomic_write(dest, b"new")
        assert open(dest, "rb").read() == b"old"
        assert not os.path.exists(dest + ".tmp")

    def test_ckpt_fsync_fault_fires_after_rename(self, tmp_path):
        """ckpt_fsync models a crash AFTER the rename but BEFORE the
        directory fsync: the new content is in place (rename done) but
        its durability is uncertain — which is exactly why the manifest
        (written after, with its own fsyncs) is the certification."""
        dest = str(tmp_path / "f")
        cw.atomic_write(dest, b"old")
        faults.arm("ckpt_fsync")
        with pytest.raises(faults.FaultInjected):
            cw.atomic_write(dest, b"new")
        assert open(dest, "rb").read() == b"new"
        assert not os.path.exists(dest + ".tmp")


class TestManifest:
    def _good_ckpt(self, tmp_path):
        cfg = step_cfg()
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        path = str(tmp_path / "ckpt")
        save_checkpoint(path, state, 1.0, cfg)
        return cfg, state, path

    def test_roundtrip_and_digests(self, tmp_path):
        cfg, state, path = self._good_ckpt(tmp_path)
        manifest = verify_checkpoint(path)
        assert set(manifest["files"]) == {"state.msgpack", "meta.json"}
        assert manifest["step"] == 0
        assert manifest["config_hash"]
        sp = os.path.join(path, "state.msgpack")
        rec = manifest["files"]["state.msgpack"]
        data = open(sp, "rb").read()
        assert rec["bytes"] == len(data)
        assert rec["sha256"] == hashlib.sha256(data).hexdigest()
        restored, best = load_checkpoint(
            path, cfg, create_train_state(jax.random.PRNGKey(1), cfg)
        )
        assert best == pytest.approx(1.0)

    def test_one_flipped_byte_raises_named_error(self, tmp_path):
        """THE integrity contract: a single bit-rotted byte in
        state.msgpack is caught BEFORE deserialization, naming the file
        and both digests."""
        cfg, state, path = self._good_ckpt(tmp_path)
        _flip_byte(os.path.join(path, "state.msgpack"))
        target = create_train_state(jax.random.PRNGKey(1), cfg)
        with pytest.raises(CheckpointError, match="state.msgpack") as ei:
            load_checkpoint(path, cfg, target)
        assert "expected sha256" in str(ei.value)
        assert not cw.is_verified(path)

    def test_truncated_file_names_sizes(self, tmp_path):
        cfg, state, path = self._good_ckpt(tmp_path)
        mp = os.path.join(path, "meta.json")
        data = open(mp, "rb").read()
        open(mp, "wb").write(data[: len(data) // 2])
        with pytest.raises(CheckpointError, match="meta.json"):
            verify_checkpoint(path)

    def test_missing_manifest_raises_and_escape_hatch(self, tmp_path):
        """A manifest-less dir is never silently loaded (the save was
        interrupted before certification, or predates manifests);
        verify=False is the explicit legacy escape hatch."""
        cfg, state, path = self._good_ckpt(tmp_path)
        os.unlink(os.path.join(path, cw.MANIFEST_NAME))
        target = create_train_state(jax.random.PRNGKey(1), cfg)
        with pytest.raises(CheckpointError, match="manifest"):
            load_checkpoint(path, cfg, target)
        restored, best = load_checkpoint(path, cfg, target, verify=False)
        assert best == pytest.approx(1.0)

    def test_truncated_manifest_is_unverified(self, tmp_path):
        cfg, state, path = self._good_ckpt(tmp_path)
        mp = os.path.join(path, cw.MANIFEST_NAME)
        open(mp, "wb").write(open(mp, "rb").read()[:20])
        assert not cw.is_verified(path)
        with pytest.raises(CheckpointError, match="manifest"):
            load_checkpoint(
                path, cfg, create_train_state(jax.random.PRNGKey(1), cfg)
            )

    def test_manifest_fault_leaves_uncertified_dir(self, tmp_path):
        """ckpt_manifest fires just before certification: the save
        fails, the dir holds complete data files but NO manifest, and
        every verification-aware reader skips it."""
        cfg = step_cfg()
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        root = str(tmp_path / "steps")
        faults.arm("ckpt_manifest")
        with pytest.raises(faults.FaultInjected):
            save_step_checkpoint(root, state, 1.0, cfg)
        [(_, path)] = cw.list_step_checkpoints(root)
        assert os.path.isfile(os.path.join(path, "state.msgpack"))
        assert not os.path.exists(os.path.join(path, cw.MANIFEST_NAME))
        resolved, skipped = cw.latest_verified_checkpoint(root)
        assert resolved is None
        assert [p for p, _ in skipped] == [path]
        # the next (un-injected) save of the same step certifies it
        save_step_checkpoint(root, state, 1.0, cfg)
        assert cw.is_verified(path)


class TestRotationGC:
    def test_keep_last_plus_keep_every(self, tmp_path):
        root = str(tmp_path / "steps")
        for s in (5, 10, 15, 20, 25, 30):
            _mk_raw_ckpt(root, s)
        kept, deleted = cw.gc_step_checkpoints(root, keep_last=2, keep_every=10)
        steps = sorted(s for s, _ in cw.list_step_checkpoints(root))
        assert steps == [10, 20, 25, 30]  # newest 2 + every 10th
        assert all(cw.is_verified(p) for _, p in cw.list_step_checkpoints(root))
        assert len(deleted) == 2

    def test_unverified_dirs_are_garbage_collected(self, tmp_path):
        root = str(tmp_path / "steps")
        _mk_raw_ckpt(root, 10)
        torn = _mk_raw_ckpt(root, 20, certify=False)  # crashed save
        kept, deleted = cw.gc_step_checkpoints(root, keep_last=3)
        assert torn in deleted
        assert [s for s, _ in cw.list_step_checkpoints(root)] == [10]

    def test_latest_resolution_falls_back_over_corruption(self, tmp_path):
        root = str(tmp_path / "steps")
        good = _mk_raw_ckpt(root, 10)
        bad = _mk_raw_ckpt(root, 20)
        _flip_byte(os.path.join(bad, "state.msgpack"))
        resolved, skipped = cw.latest_verified_checkpoint(root)
        assert resolved == good
        assert [p for p, _ in skipped] == [bad]

    def test_gc_crash_leaves_uncertified_never_torn_certified(self, tmp_path):
        """Crash-safe delete ordering: ckpt_gc fires AFTER the victim's
        manifest is removed but BEFORE its data goes. The survivor set
        must contain no certified-but-partial dir — the victim is
        merely uncertified (skipped by every reader) and the next GC
        finishes the job."""
        root = str(tmp_path / "steps")
        _mk_raw_ckpt(root, 10)
        _mk_raw_ckpt(root, 20)
        _mk_raw_ckpt(root, 30)
        faults.arm("ckpt_gc")
        with pytest.raises(faults.FaultInjected):
            cw.gc_step_checkpoints(root, keep_last=1)
        victim = os.path.join(root, cw.step_dir_name(10))
        assert os.path.isdir(victim)
        assert not os.path.exists(os.path.join(victim, cw.MANIFEST_NAME))
        assert not cw.is_verified(victim)
        resolved, _ = cw.latest_verified_checkpoint(root)
        assert resolved == os.path.join(root, cw.step_dir_name(30))
        # un-injected GC completes the interrupted retention pass
        cw.gc_step_checkpoints(root, keep_last=1)
        assert [s for s, _ in cw.list_step_checkpoints(root)] == [30]


class TestAsyncWriter:
    def test_submit_is_nonblocking_while_job_runs(self):
        """The async contract: submit() hands the job to the writer
        thread and returns immediately; only a SECOND submit while the
        first is still in flight blocks (back-pressure), and the
        blocked time is reported."""
        w = AsyncCheckpointWriter()
        gate = threading.Event()
        ran = []
        t0 = time.perf_counter()
        blocked1 = w.submit(lambda: (gate.wait(10), ran.append(1)))
        submit_cost = time.perf_counter() - t0
        assert blocked1 < 0.05  # idle writer: no back-pressure
        assert submit_cost < 0.5  # returned while the job is running
        assert not ran  # the job really is on the other thread
        threading.Timer(0.3, gate.set).start()
        blocked2 = w.submit(lambda: ran.append(2))
        assert blocked2 >= 0.2  # back-pressure until job 1 drained
        w.close()
        assert ran == [1, 2]
        assert w.saves_completed == 2
        assert w.last_save_s is not None

    def test_job_error_surfaces_without_dropping_next_job(self):
        """A transient failure loses exactly the save that failed: the
        error surfaces on the NEXT submit, but only after that submit's
        (healthy) job is enqueued — one bad save never costs two."""
        w = AsyncCheckpointWriter()
        ran = []
        w.submit(lambda: (_ for _ in ()).throw(ValueError("disk on fire")))
        time.sleep(0.1)
        with pytest.raises(ValueError, match="disk on fire"):
            w.submit(lambda: ran.append(1))
        w.close()
        assert ran == [1]  # the follow-up snapshot still landed
        # failed jobs never pollute the save telemetry
        assert w.saves_completed == 1

    def test_close_drains_pending_job(self, tmp_path):
        w = AsyncCheckpointWriter()
        marker = str(tmp_path / "done")
        w.submit(lambda: (time.sleep(0.2), open(marker, "w").write("x")))
        w.close()
        assert os.path.exists(marker)

    def test_histograms_observe(self):
        from differential_transformer_replication_tpu.obs import Registry

        reg = Registry()
        w = AsyncCheckpointWriter(
            save_hist=reg.histogram("ckpt_save_seconds"),
            blocked_hist=reg.histogram("ckpt_blocked_seconds"),
        )
        w.submit(lambda: None)
        w.close()
        assert reg.histogram("ckpt_save_seconds").snapshot()["count"] == 1
        assert reg.histogram("ckpt_blocked_seconds").snapshot()["count"] == 1

    def test_drained_property_tracks_thread_lifecycle(self):
        """`drained` is the rescue save's gate: False while a job is
        in flight OR the writer is merely idle-but-open, True only
        once close() has stopped the thread — and a post-close submit
        raises rather than interleaving with a drained tree."""
        w = AsyncCheckpointWriter()
        assert not w.drained  # open, idle: a job could still arrive
        gate = threading.Event()
        w.submit(lambda: gate.wait(10))
        assert not w.drained  # in flight
        gate.set()
        w.close()
        assert w.drained
        with pytest.raises(RuntimeError, match="closed"):
            w.submit(lambda: None)


class TestResolveAuto:
    def test_picks_newest_verified_across_sources(self, tmp_path):
        cfg = step_cfg(
            checkpoint_path=str(tmp_path / "best.ckpt"),
            ckpt_dir=str(tmp_path / "steps"),
        )
        state = create_train_state(jax.random.PRNGKey(0), cfg)
        save_checkpoint(cfg.checkpoint_path, state, 1.0, cfg)  # step 0
        root = cfg.resolved_ckpt_dir()
        good = _mk_raw_ckpt(root, 10)
        bad = _mk_raw_ckpt(root, 20)
        _flip_byte(os.path.join(bad, "state.msgpack"))
        resolved, skipped = resolve_resume_auto(cfg)
        assert resolved == good
        assert [p for p, _ in skipped] == [bad]

    def test_no_candidates_resolves_none(self, tmp_path):
        cfg = step_cfg(
            checkpoint_path=str(tmp_path / "nope.ckpt"),
            ckpt_dir=str(tmp_path / "steps"),
        )
        resolved, skipped = resolve_resume_auto(cfg)
        assert resolved is None and skipped == []


class TestTrainerIntegration:
    def test_async_step_checkpoints_verified_with_compile_pin(self, tmp_path):
        """Acceptance: periodic async checkpoints land certified, the
        rotation honors keep_last/keep_every, ckpt telemetry rides the
        metrics records, and the instrumented+checkpointed loop still
        compiles its step exactly ONCE (snapshotting must not
        retrace)."""
        cfg = tiny_cfg(tmp_path, ckpt_interval=5, ckpt_keep_last=2,
                       ckpt_keep_every=10)
        state = train(cfg)
        assert int(state["step"]) == 20
        root = cfg.resolved_ckpt_dir()
        entries = cw.list_step_checkpoints(root)
        assert [s for s, _ in entries] == [10, 15, 20]
        assert all(cw.is_verified(p) for _, p in entries)
        # best/last checkpoints are certified too
        assert cw.is_verified(cfg.checkpoint_path)
        assert cw.is_verified(cfg.last_checkpoint_path)
        recs = [json.loads(l) for l in open(cfg.metrics_path)]
        steps = [r for r in recs if "ckpt_blocked_ms" in r]
        assert steps, "ckpt telemetry missing from metrics.jsonl"
        assert any("ckpt_save_ms" in r for r in steps)
        pins = [r["compile_events"] for r in recs if "compile_events" in r]
        assert pins and pins[-1] == 1

    def test_writer_stall_back_pressure_loop_keeps_stepping(
        self, tmp_path, monkeypatch
    ):
        """ckpt_hang stalls the FIRST async save on the writer thread:
        the run completes (the loop stepped right through the stall),
        the next interval's submit reports back-pressure (a save was
        genuinely still in flight — impossible with inline writes), and
        every checkpoint still certifies."""
        monkeypatch.setenv(faults.CKPT_HANG_ENV_VAR, "1.0")
        cfg = tiny_cfg(tmp_path, faults="ckpt_hang@1", ckpt_interval=4,
                       max_iters=12, log_interval=1, eval_interval=50)
        t0 = time.perf_counter()
        state = train(cfg)
        assert int(state["step"]) == 12
        entries = cw.list_step_checkpoints(cfg.resolved_ckpt_dir())
        assert [s for s, _ in entries] == [4, 8, 12]  # keep_last=3 default
        assert all(cw.is_verified(p) for _, p in entries)
        recs = [json.loads(l) for l in open(cfg.metrics_path)]
        blocked = sum(r.get("ckpt_blocked_ms", 0.0) for r in recs)
        assert blocked > 0.0  # the save at 8 waited on the stalled save at 4

    def test_sigterm_rescue_waits_for_inflight_async_save(
        self, tmp_path, monkeypatch
    ):
        """Regression (drain ordering): a SIGTERM graceful stop arriving
        while an async periodic save is STALLED in flight (ckpt_hang on
        the writer thread) must drain the writer BEFORE the inline
        rescue save — never interleave two writers over one tree. Both
        checkpoints certify, and the manifests' written_at order proves
        the stalled save landed first."""
        monkeypatch.setenv(faults.CKPT_HANG_ENV_VAR, "1.0")
        cfg = tiny_cfg(tmp_path, faults="ckpt_hang@2,sigterm@9",
                       ckpt_interval=4, log_interval=1, eval_interval=50)
        state = train(cfg)
        stopped = int(state["step"])
        assert stopped < 20  # the graceful stop really cut the run short
        # the stalled step-8 save finished and certified (drained, not
        # abandoned), and the rescue checkpoint certified after it
        step8 = os.path.join(cfg.resolved_ckpt_dir(), cw.step_dir_name(8))
        assert cw.is_verified(step8)
        assert cw.is_verified(cfg.last_checkpoint_path)
        m_step = cw.read_manifest(step8)
        m_rescue = cw.read_manifest(cfg.last_checkpoint_path)
        assert m_rescue["written_at"] >= m_step["written_at"]
        # the rescue state resumes at the stop iteration
        target = create_train_state(jax.random.PRNGKey(0), cfg)
        restored, _ = load_checkpoint(cfg.last_checkpoint_path, cfg, target)
        assert int(restored["step"]) == stopped

    def test_resume_auto_skips_corrupt_and_falls_back(self, tmp_path, capsys):
        """--resume-from auto end to end: with the newest checkpoints
        corrupted (torn rescue save, bit-rotted newest step dir), the
        trainer resumes from the newest one that verifies instead of
        crashing or silently loading garbage."""
        cfg = tiny_cfg(tmp_path, ckpt_interval=5, ckpt_keep_last=4)
        train(cfg)
        root = cfg.resolved_ckpt_dir()
        # corrupt everything at step 20: the rescue last-ckpt, the best
        # ckpt (also step 20 here), and the newest step dir
        _flip_byte(os.path.join(cfg.last_checkpoint_path, "state.msgpack"))
        _flip_byte(os.path.join(cfg.checkpoint_path, "state.msgpack"))
        _flip_byte(
            os.path.join(root, cw.step_dir_name(20), "state.msgpack")
        )
        cfg2 = cfg.replace(max_iters=25, resume_from="auto")
        state = train(cfg2)
        out = capsys.readouterr().out
        assert "skipping unverified checkpoint" in out
        assert f"resuming from {os.path.join(root, cw.step_dir_name(15))}" in out
        assert int(state["step"]) == 25

    def test_resume_auto_fresh_start_when_nothing_exists(self, tmp_path, capsys):
        cfg = tiny_cfg(tmp_path, max_iters=6, eval_interval=50,
                       resume_from="auto")
        state = train(cfg)
        assert "no verified checkpoint found; starting fresh" in \
            capsys.readouterr().out
        assert int(state["step"]) == 6


def _load_supervisor_module():
    spec = importlib.util.spec_from_file_location("train_supervisor", SUPERVISOR)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestSupervisorVerifiedResume:
    def test_tree_resolves_newest_verified(self, tmp_path):
        sup = _load_supervisor_module()
        root = str(tmp_path / "steps")
        good = _mk_raw_ckpt(root, 10)
        bad = _mk_raw_ckpt(root, 20)
        _flip_byte(os.path.join(bad, "state.msgpack"))
        assert sup.resolve_resume_ckpt(root) == good

    def test_single_dir_verified_or_skipped(self, tmp_path):
        sup = _load_supervisor_module()
        path = _mk_raw_ckpt(str(tmp_path), 5)
        assert sup.resolve_resume_ckpt(path) == path
        _flip_byte(os.path.join(path, "state.msgpack"))
        assert sup.resolve_resume_ckpt(path) is None

    def test_legacy_dir_without_manifest_not_injected(self, tmp_path):
        """A manifest-less dir must NOT be injected: the trainer's
        verified load would reject it on every relaunch, wedging the
        restart loop on a CheckpointError (certify legacy dirs once
        with ckpt_doctor --adopt-legacy instead)."""
        sup = _load_supervisor_module()
        path = str(tmp_path / "legacy.ckpt")
        os.makedirs(path)
        open(os.path.join(path, "state.msgpack"), "wb").write(b"x")
        assert sup.resolve_resume_ckpt(path) is None
        assert sup.resolve_resume_ckpt(str(tmp_path / "missing")) is None
        assert sup.resolve_resume_ckpt(None) is None
        # adopted via the doctor, the same dir becomes injectable
        cw.write_manifest(path, step=0)
        assert sup.resolve_resume_ckpt(path) == path


class TestCkptDoctor:
    def _run(self, *args):
        proc = subprocess.run(
            [sys.executable, DOCTOR, *args],
            capture_output=True, text=True, timeout=60,
        )
        summary = json.loads(proc.stdout.strip().splitlines()[-1])
        return proc, summary

    def _tree(self, tmp_path):
        root = str(tmp_path / "steps")
        _mk_raw_ckpt(root, 10)
        bad = _mk_raw_ckpt(root, 20)
        _flip_byte(os.path.join(bad, "state.msgpack"))
        legacy = os.path.join(str(tmp_path), "legacy.ckpt")
        os.makedirs(legacy)
        open(os.path.join(legacy, "state.msgpack"), "wb").write(b"s")
        open(os.path.join(legacy, "meta.json"), "w").write(
            '{"iter_num": 3, "best_val_loss": 1.0}'
        )
        return root, bad, legacy

    def test_list_verify_and_check_gate(self, tmp_path):
        root, bad, legacy = self._tree(tmp_path)
        proc, summary = self._run(root, legacy, "--check")
        assert proc.returncode == 1
        assert summary["checkpoints"] == 3
        assert summary["verified"] == 1
        assert summary["corrupt"] == 1
        assert summary["legacy"] == 1
        assert summary["newest_verified_step"] == 10
        assert "CHECK FAILED" in proc.stderr

    def test_repair_and_adopt_make_check_pass(self, tmp_path):
        root, bad, legacy = self._tree(tmp_path)
        proc, summary = self._run(
            root, legacy, "--repair", "--adopt-legacy", "--check"
        )
        assert proc.returncode == 0, proc.stderr
        assert summary["repaired"] == [bad]
        assert summary["adopted"] == [legacy]
        assert summary["corrupt"] == 0
        assert not os.path.exists(bad)
        assert cw.is_verified(legacy)
        # adopted manifest records the meta's step
        assert cw.read_manifest(legacy)["step"] == 3

    def test_walks_nested_step_trees(self, tmp_path):
        """`ckpt_doctor.py runs/` must find checkpoints nested under
        run subdirectories (`runs/exp.steps/step-*`), not just
        immediate children."""
        run = tmp_path / "runs"
        _mk_raw_ckpt(str(run / "exp.steps"), 10)
        bad = _mk_raw_ckpt(str(run / "other" / "exp2.steps"), 20)
        _flip_byte(os.path.join(bad, "state.msgpack"))
        proc, summary = self._run(str(run))
        assert summary["checkpoints"] == 2
        assert summary["verified"] == 1
        assert summary["corrupt"] == 1
        assert summary["newest_verified_step"] == 10


def _train_env(**extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    env.pop(faults.ENV_VAR, None)
    env.update(extra)
    return env


def _run_chaos(tmp_path, name, *extra, supervised=False, env_extra=None):
    """One train.py run with rotating async checkpoints + --resume-from
    auto (optionally supervised). Faults ride DTX_FAULTS, which the
    supervisor strips on restarts."""
    d = tmp_path / name
    d.mkdir()
    env = _train_env(**(env_extra or {}))
    cmd = [
        sys.executable, TRAIN_PY, "--model", "diff",
        "--dataset", "synthetic", "--num-train-samples", "200",
        "--vocab-size", "256", "--n-embd", "32", "--n-head", "2",
        "--n-layer", "2", "--block-size", "16",
        "--compute-dtype", "float32", "--micro-batch-size", "4",
        "--max-iters", "24", "--eval-interval", "8", "--eval-iters", "2",
        "--learning-rate", "3e-3", "--warmup-iters", "5", "--seed", "7",
        "--tokenizer-dir", str(tmp_path / "tokenizer"),
        "--checkpoint-path", str(d / "best.ckpt"),
        "--last-checkpoint-path", str(d / "last.ckpt"),
        "--metrics-path", str(d / "metrics.jsonl"),
        "--ckpt-interval", "6", "--ckpt-keep-last", "10",
        "--resume-from", "auto",
        *extra,
    ]
    if supervised:
        cmd = [
            sys.executable, SUPERVISOR, "--backoff-base", "0.05",
            "--max-restarts", "3",
            "--restart-log", str(d / "restarts.json"), "--",
        ] + cmd
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=600, env=env
    )
    return d, proc


@pytest.mark.slow
def test_sigkill_during_async_save_resumes_verified_and_bit_identical(tmp_path):
    """THE durability chaos test: a run is SIGKILLed while an async
    step-checkpoint save is STALLED in flight (ckpt_hang on the writer
    thread), leaving that save torn/uncertified. The supervisor
    restarts it, `--resume-from auto` resolves the newest checkpoint
    that passes manifest verification (falling back past the torn one),
    and the finished run is bit-identical to an uninterrupted run. The
    torn dir is garbage-collected by a later save's retention pass."""
    a, proc_a = _run_chaos(tmp_path, "uninterrupted")
    assert proc_a.returncode == 0, proc_a.stderr[-2000:]

    b, proc_b = _run_chaos(
        tmp_path, "killed", supervised=True,
        env_extra={
            # save @12 stalls 5s on the writer; iters 13-14 keep
            # stepping; the SIGKILL at 14 lands mid-save
            faults.ENV_VAR: "ckpt_hang@2,sigkill@14",
            faults.CKPT_HANG_ENV_VAR: "5.0",
        },
    )
    assert proc_b.returncode == 0, proc_b.stderr[-2000:]
    records = [json.loads(l) for l in open(b / "restarts.json")]
    assert [r["outcome"] for r in records] == ["sigkill", "clean"]
    assert "--resume-from auto: resuming from" in proc_b.stdout

    # bit-identical final state vs the uninterrupted run
    sa = open(a / "last.ckpt" / "state.msgpack", "rb").read()
    sb = open(b / "last.ckpt" / "state.msgpack", "rb").read()
    assert sa == sb
    # every surviving checkpoint certifies; the torn step-12 save never
    # became loadable and was GC'd by a later retention pass
    for d in (a, b):
        entries = cw.list_step_checkpoints(str(d / "best.steps"))
        assert all(cw.is_verified(p) for _, p in entries)
        assert 24 in [s for s, _ in entries]
    assert cw.is_verified(str(b / "last.ckpt"))
