"""Serving resilience layer (ISSUE 3): engine supervision, server-side
deadlines, graceful drain, watchdog, retry client.

The load-bearing contracts:

- a mid-batch engine crash NEVER hangs a caller — every in-flight
  request fails with a typed, retriable ``EngineCrashError`` while the
  supervised runner rebuilds the slot pool from params and keeps
  serving; wait-queue entries ride through the restart verbatim and the
  restarted engine is bit-identical to a fresh one;
- expired requests are shed at admission and retired mid-decode (KV
  slot reclaimed) with a typed ``DeadlineExceededError``;
- ``drain()`` stops admission (503 + Retry-After over HTTP), finishes
  everything in flight within the budget, and loses nothing;
- all of it is host-side bookkeeping: zero new compiles (pinned below).

Quick tier: deterministic fault-point tests. Slow tier: chaos tests
under real concurrent load (mirrors tests/test_faults.py's tiering).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from functools import lru_cache
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.config import (
    ModelConfig,
    ServingConfig,
)
from differential_transformer_replication_tpu.models import (
    generate_cached,
    init_model,
)
from differential_transformer_replication_tpu.serving import (
    DeadlineExceededError,
    EngineCrashError,
    EngineRunner,
    QueueFullError,
    Scheduler,
    ServingClient,
    ServingEngine,
    ShuttingDownError,
    backoff_delay,
    call_with_retries,
    http_post_json_with_retries,
    serve,
)
from differential_transformer_replication_tpu.serving.request import Request
from differential_transformer_replication_tpu.serving.scheduler import (
    ACTIVE,
    FREE,
)
from differential_transformer_replication_tpu.utils import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _cfg(kind, vocab=61):
    return ModelConfig(
        model=kind, vocab_size=vocab, n_embd=32, n_head=2, n_layer=2,
        block_size=32, dropout=0.0, n_terms=3, compute_dtype="float32",
    )


@lru_cache(maxsize=None)
def _setup(kind, vocab=61):
    cfg = _cfg(kind, vocab)
    return cfg, init_model(jax.random.PRNGKey(0), cfg)


def _prompts(lens, vocab, seed=1):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=L).tolist() for L in lens]


def _ref_greedy(params, cfg, prompt, n):
    out = generate_cached(
        params, jnp.asarray(prompt, jnp.int32)[None], cfg, n,
        jax.random.PRNGKey(0), temperature=0.0,
    )
    return np.asarray(out)[0, len(prompt):].tolist()


def _serving(**kw):
    kw.setdefault("num_slots", 1)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("prefill_budget", 8)
    kw.setdefault("restart_backoff_s", 0.01)
    kw.setdefault("restart_backoff_max_s", 0.05)
    return ServingConfig(**kw)


# -- fault-spec parsing -------------------------------------------------


class TestServeFaultSpec:
    def test_parse_and_one_shot(self):
        faults.arm("serve_raise@3,serve_corrupt@5")
        assert faults.armed()
        faults.serve_fire(2)  # not armed for 2: no-op
        with pytest.raises(faults.FaultInjected, match="iteration 3"):
            faults.serve_fire(3)
        faults.serve_fire(3)  # one-shot: a replayed iteration is safe
        assert faults.serve_corrupt_at(4) is False
        assert faults.serve_corrupt_at(5) is True
        assert faults.serve_corrupt_at(5) is False  # one-shot

    def test_hang_honors_env_override(self, monkeypatch):
        monkeypatch.setenv(faults.HANG_ENV_VAR, "0.15")
        faults.arm("serve_hang@1")
        t0 = time.perf_counter()
        faults.serve_fire(1)
        assert time.perf_counter() - t0 >= 0.14
        t0 = time.perf_counter()
        faults.serve_fire(1)  # disarmed
        assert time.perf_counter() - t0 < 0.1

    def test_unknown_kind_still_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            faults.arm("serve_explode@3")


# -- scheduler deadline bookkeeping ------------------------------------


class TestSchedulerDeadlines:
    def _sched(self, **kw):
        return Scheduler(ServingConfig(**kw))

    def test_shed_expired_only_drops_expired(self):
        s = self._sched(num_slots=1)
        for i, dl in enumerate([0.0, 5.0, 100.0]):  # 0.0 = no deadline
            s.submit(Request.make(i, [1, 2]), np.ones(2, np.int32), 0.0, dl)
        shed = s.shed_expired(now=10.0)
        assert [e[0].request_id for e in shed] == [1]
        assert s.queue_len() == 2  # no-deadline + future-deadline stay
        assert s.shed_expired(now=10.0) == []  # idempotent

    def test_deadline_rides_admission_into_slot(self):
        s = self._sched(num_slots=1)
        s.submit(Request.make(0, [1, 2]), np.ones(2, np.int32), 0.0, 42.0)
        s.plan()
        slot = s.slots[0]
        assert slot.deadline == 42.0
        assert s.expired_slots(now=41.0) == []
        assert s.expired_slots(now=42.0) == [slot]
        s.retire(slot)
        assert slot.deadline == 0.0  # reset with the rest of the slot

    def test_cancel_still_works_with_deadline_entries(self):
        s = self._sched(num_slots=1)
        s.submit(Request.make(0, [1, 2]), np.ones(2, np.int32), 0.0, 9.0)
        assert s.cancel(0) is True
        assert s.queue_len() == 0


# -- retry helpers ------------------------------------------------------


class TestRetryHelpers:
    def test_backoff_envelope_and_retry_after_floor(self):
        import random

        rng = random.Random(0)
        for attempt in range(6):
            d = backoff_delay(attempt, base=0.1, cap=2.0, rng=rng)
            assert 0.0 <= d <= min(2.0, 0.1 * 2 ** attempt)
        # the server's Retry-After floors the jittered delay
        d = backoff_delay(0, base=0.1, cap=2.0, retry_after=7.5, rng=rng)
        assert d >= 7.5

    def test_call_with_retries_counts_and_rethrows_typed(self):
        sleeps = []
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise QueueFullError("full")
            return "ok"

        out, retries = call_with_retries(
            flaky, max_retries=5, retriable=(QueueFullError,),
            sleep=sleeps.append,
        )
        assert out == "ok" and retries == 2 and len(sleeps) == 2

        def always():
            raise EngineCrashError("dead")

        with pytest.raises(EngineCrashError):  # typed error survives
            call_with_retries(
                always, max_retries=1, retriable=(EngineCrashError,),
                sleep=sleeps.append,
            )
        with pytest.raises(ValueError):  # non-retriable: immediate
            call_with_retries(
                lambda: (_ for _ in ()).throw(ValueError("bad")),
                max_retries=5, retriable=(QueueFullError,),
                sleep=sleeps.append,
            )

    def test_retriable_false_instance_short_circuits(self):
        """A permanently failed engine raises the same CLASS as a
        restarting one but with retriable=False — no retries, and the
        attempts burned are reported on the exception."""

        def dead():
            e = EngineCrashError("restart budget exhausted")
            e.retriable = False
            raise e

        sleeps = []
        with pytest.raises(EngineCrashError) as ei:
            call_with_retries(dead, max_retries=5,
                              retriable=(EngineCrashError,),
                              sleep=sleeps.append)
        assert sleeps == []  # failed over immediately
        assert ei.value.retry_attempts == 0

    def test_http_non_retriable_503_codes_return_immediately(self):
        hits = {"n": 0}

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                hits["n"] += 1
                body = json.dumps(
                    {"error": "generation timed out", "code": "timeout"}
                ).encode()
                self.send_response(503)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            status, body, retries = http_post_json_with_retries(
                f"http://127.0.0.1:{httpd.server_address[1]}/x", {},
                max_retries=5, sleep=lambda s: None,
            )
            # a timeout-coded 503 already burned its full generation
            # budget server-side: retrying it amplifies the overload
            assert status == 503 and retries == 0 and hits["n"] == 1
            assert body["code"] == "timeout"
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_http_retries_honor_retry_after_on_503(self):
        hits = {"n": 0}

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                hits["n"] += 1
                body = json.dumps({"ok": hits["n"]}).encode()
                code = 503 if hits["n"] == 1 else 200
                self.send_response(code)
                if code == 503:
                    self.send_header("Retry-After", "0.05")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            sleeps = []
            status, body, retries = http_post_json_with_retries(
                f"http://127.0.0.1:{httpd.server_address[1]}/x", {},
                max_retries=3, sleep=sleeps.append,
            )
            assert status == 200 and body == {"ok": 2} and retries == 1
            assert sleeps and sleeps[0] >= 0.05  # honored Retry-After
        finally:
            httpd.shutdown()
            httpd.server_close()


# -- server-side deadlines ----------------------------------------------


def test_deadline_sheds_expired_at_admission():
    """A request whose deadline passed while queued never gets a slot:
    finish_reason 'deadline', zero tokens, no device work burned."""
    cfg, params = _setup("control")
    eng = ServingEngine(params, cfg, _serving())
    p = _prompts([4], cfg.vocab_size, seed=20)[0]
    rid = eng.submit(p, max_new_tokens=4, temperature=0.0,
                     deadline=time.perf_counter() - 1.0)
    outs = eng.step()
    assert [o.request_id for o in outs] == [rid]
    assert outs[0].finish_reason == "deadline"
    assert outs[0].tokens == []
    assert eng.stats["deadline_expired"] == 1
    assert eng.stats["prefill_tokens"] == 0  # truly shed, never prefilled
    assert all(s.state == FREE for s in eng.scheduler.slots)
    assert not eng.scheduler.has_work()


def test_deadline_retires_slot_mid_decode_and_reclaims_it():
    """An ACTIVE slot whose deadline passes mid-decode is retired with
    its partial tokens; the reclaimed slot serves the next request with
    bit-exact output (ring-mask invariant, same as cancel)."""
    cfg, params = _setup("control")
    eng = ServingEngine(params, cfg, _serving())
    p = _prompts([5], cfg.vocab_size, seed=21)[0]
    rid = eng.submit(p, max_new_tokens=24, temperature=0.0,
                     deadline=time.perf_counter() + 3600)
    for _ in range(3):  # prefill + a couple of decode steps
        eng.step()
    slot = eng.scheduler.slots[0]
    assert slot.state == ACTIVE and slot.request.request_id == rid
    n_before = len(slot.generated)
    assert n_before >= 1
    slot.deadline = time.perf_counter() - 1.0  # force expiry mid-decode
    outs = eng.step()
    assert [o.request_id for o in outs] == [rid]
    assert outs[0].finish_reason == "deadline"
    assert len(outs[0].tokens) == n_before  # partial output delivered
    assert outs[0].tokens == _ref_greedy(params, cfg, p, 24)[:n_before]
    assert eng.scheduler.slots[0].state == FREE  # KV slot reclaimed
    p2 = _prompts([6], cfg.vocab_size, seed=22)[0]
    out = eng.generate([p2], max_new_tokens=4, temperature=0.0)[0]
    assert out.tokens == _ref_greedy(params, cfg, p2, 4)


def test_default_deadline_from_config():
    cfg, params = _setup("control")
    eng = ServingEngine(
        params, cfg, _serving(default_deadline_s=0.5),
    )
    eng.submit(_prompts([4], cfg.vocab_size)[0], max_new_tokens=4)
    _req, _p, t_submit, deadline, _trace = eng.scheduler.queue[0]
    assert deadline == pytest.approx(t_submit + 0.5, abs=0.05)


def test_runner_delivers_typed_deadline_error():
    """Through the runner/client: an expired request raises
    DeadlineExceededError carrying the partial output, not a hang or a
    bare timeout."""
    cfg, params = _setup("control")
    client = ServingClient(ServingEngine(params, cfg, _serving()))
    try:
        with pytest.raises(DeadlineExceededError) as ei:
            client.generate(
                _prompts([4], cfg.vocab_size, seed=23)[0],
                max_new_tokens=4, temperature=0.0,
                deadline_s=0.0, timeout=60,
            )
        assert ei.value.output is not None
        assert ei.value.output.finish_reason == "deadline"
        assert client.stats["deadline_expired"] == 1
        # the engine is unharmed: a normal request still completes
        p = _prompts([4], cfg.vocab_size, seed=24)[0]
        out = client.generate(p, max_new_tokens=4, temperature=0.0,
                              timeout=60)
        assert out.tokens == _ref_greedy(params, cfg, p, 4)
    finally:
        client.close()


# -- engine supervision -------------------------------------------------


def test_step_exception_fails_pendings_promptly_without_restart():
    """THE hang-bug regression: with the restart budget at zero, an
    exception inside the engine step must fail every queued/in-flight
    pending promptly with a typed error — the old behavior delivered
    the raw exception only to admitted waiters and relied on the dead
    thread's stop flag for the rest."""
    cfg, params = _setup("control")
    serving = _serving(max_restarts=0)
    client = ServingClient(ServingEngine(params, cfg, serving))
    faults.arm("serve_raise@1")
    prompts = _prompts([4, 5, 6], cfg.vocab_size, seed=25)
    handles = [
        client.runner.submit(p, max_new_tokens=8, temperature=0.0)
        for p in prompts
    ]
    for h in handles:
        assert h.done.wait(60), "pending stranded after engine crash"
        assert isinstance(h.error, EngineCrashError)
    assert client.status() == "failed"
    with pytest.raises(EngineCrashError):  # submissions refused, typed
        client.runner.submit(prompts[0], max_new_tokens=2)
    client.close()


def test_supervised_restart_preserves_queue_and_is_bit_identical():
    """Tentpole pin: a mid-batch crash fails the slot-holding request
    with EngineCrashError, preserves wait-queue entries verbatim, and
    the rebuilt engine finishes them with exactly the tokens an
    uncrashed engine produces."""
    cfg, params = _setup("control")
    client = ServingClient(ServingEngine(
        params, cfg, _serving(max_restarts=2),
    ))
    p_infl, p_queued = _prompts([5, 7], cfg.vocab_size, seed=26)
    faults.arm("serve_raise@2")  # request 0 holds the slot by then
    try:
        a = client.runner.submit(p_infl, max_new_tokens=16, temperature=0.0)
        b = client.runner.submit(p_queued, max_new_tokens=6, temperature=0.0)
        assert a.done.wait(60) and b.done.wait(60)
        assert isinstance(a.error, EngineCrashError)  # in-flight: typed fail
        assert b.error is None  # queued: rode through the restart
        assert b.result.tokens == _ref_greedy(params, cfg, p_queued, 6)
        assert client.runner.restarts == 1
        assert client.stats["engine_restarts"] == 1
        # the restarted engine serves a fresh request bit-identically
        p = _prompts([6], cfg.vocab_size, seed=27)[0]
        out = client.generate(p, max_new_tokens=6, temperature=0.0,
                              timeout=60)
        assert out.tokens == _ref_greedy(params, cfg, p, 6)
        assert client.status() == "healthy"
    finally:
        client.close()


def test_slot_corruption_trips_finite_guard_and_recovers():
    """serve_corrupt NaN-poisons an active slot's KV rows: the sampler's
    finite-logits guard turns that into EngineCrashError (never a
    silently-garbage token), and the supervised rebuild recovers."""
    cfg, params = _setup("control")
    client = ServingClient(ServingEngine(
        params, cfg, _serving(max_restarts=2),
    ))
    faults.arm("serve_corrupt@2")
    try:
        a = client.runner.submit(
            _prompts([5], cfg.vocab_size, seed=28)[0],
            max_new_tokens=16, temperature=0.0,
        )
        assert a.done.wait(60)
        assert isinstance(a.error, EngineCrashError)
        assert "non-finite" in str(a.error)
        p = _prompts([4], cfg.vocab_size, seed=29)[0]
        out = client.generate(p, max_new_tokens=4, temperature=0.0,
                              timeout=60)
        assert out.tokens == _ref_greedy(params, cfg, p, 4)
    finally:
        client.close()


def test_outputs_finished_before_mid_step_crash_survive():
    """A request that finishes EARLY in a step whose decode then
    crashes is already retired from the scheduler — invisible to both
    the lost-list and the preserved queue. take_finished() must hand it
    back, or its caller hangs forever (code-review regression)."""
    cfg, params = _setup("control")
    eng = ServingEngine(params, cfg, _serving(num_slots=2))
    p_long, p_short = _prompts([5, 4], cfg.vocab_size, seed=40)
    rid_b = eng.submit(p_long, max_new_tokens=16, temperature=0.0)
    eng.step()  # B prefills + goes ACTIVE
    faults.arm(f"serve_corrupt@{eng.stats['iterations']}")
    # A finishes during next step's PREFILL phase (single token); the
    # corruption then poisons ACTIVE B and the decode raises
    rid_a = eng.submit(p_short, max_new_tokens=1, temperature=0.0)
    with pytest.raises(EngineCrashError):
        eng.step()
    outs = eng.take_finished()
    assert [o.request_id for o in outs] == [rid_a]
    assert outs[0].finish_reason == "length"
    assert outs[0].tokens == _ref_greedy(params, cfg, p_short, 1)
    assert eng.reset_after_crash() == [rid_b]
    assert eng.take_finished() == []  # drained exactly once


def test_runner_delivers_pre_crash_outputs_to_waiters():
    """Runner-level delivery of the buffer: the finished-before-crash
    request gets its RESULT; only the genuinely lost one gets the
    typed error."""

    class _CrashAfterFinish:
        def __init__(self):
            self.serving = ServingConfig(num_slots=1, max_restarts=1)
            self.stats = {"rejected": 0}
            self.q = []
            self.crashed = False

        def queue_len(self):
            return len(self.q)

        def has_work(self):
            return bool(self.q)

        def submit(self, prompt, params=None):
            self.q.append(len(self.q))
            return len(self.q) - 1

        def cancel(self, rid):
            return False

        def take_finished(self):
            if not self.crashed:
                return []
            from differential_transformer_replication_tpu.serving import (
                RequestOutput,
            )

            return [RequestOutput(request_id=0, prompt=[1], tokens=[7],
                                  finish_reason="length")]

        def reset_after_crash(self):
            self.q.clear()
            return [1]  # rid 1 was "in flight"

        def step(self):
            if len(self.q) < 2:  # wait until both requests are in hand
                time.sleep(0.002)
                return []
            self.crashed = True
            raise RuntimeError("boom mid-step")

    runner = EngineRunner(_CrashAfterFinish())
    try:
        h0 = runner.submit([1], max_new_tokens=2)
        h1 = runner.submit([2], max_new_tokens=2)
        assert h0.done.wait(30) and h1.done.wait(30)
        assert h0.error is None and h0.result.tokens == [7]
        assert isinstance(h1.error, EngineCrashError)
    finally:
        runner.close()


def test_restart_budget_exhaustion_fails_hard():
    cfg, params = _setup("control")
    client = ServingClient(ServingEngine(
        params, cfg, _serving(max_restarts=1),
    ))
    faults.arm("serve_raise@1,serve_raise@2,serve_raise@3")
    try:
        handles = [
            client.runner.submit(p, max_new_tokens=8, temperature=0.0)
            for p in _prompts([4, 5], cfg.vocab_size, seed=30)
        ]
        for h in handles:
            assert h.done.wait(60)
            assert isinstance(h.error, EngineCrashError)
        assert client.status() == "failed"
        assert client.runner.restarts == 2  # 1 rebuild + the fatal one
    finally:
        client.close()


def test_deadline_drain_restart_machinery_adds_zero_recompiles():
    """Compile pin (satellite): deadlines, drain bookkeeping and a
    full crash-restart cycle are host-side only — not one new cache
    entry on any of the engine's jitted closures."""
    cfg, params = _setup("control", vocab=47)  # fresh compile-cache key
    serving = _serving(num_slots=2, max_restarts=3)
    eng = ServingEngine(params, cfg, serving)
    eng.generate(_prompts([3, 9, 6], cfg.vocab_size, seed=31),
                 max_new_tokens=4, temperature=0.0)
    baseline = eng.compile_stats()
    assert baseline["decode"] == 1

    # deadline wave: one shed at admission, one expiring mid-decode
    eng.submit(_prompts([4], cfg.vocab_size, seed=32)[0],
               max_new_tokens=4, deadline=time.perf_counter() - 1.0)
    eng.submit(_prompts([5], cfg.vocab_size, seed=33)[0],
               max_new_tokens=12, temperature=0.0,
               deadline=time.perf_counter() + 3600)
    eng.step(); eng.step()
    for s in eng.scheduler.slots:
        if s.state != FREE:
            s.deadline = time.perf_counter() - 1.0
    eng.run()
    # crash/restart cycle on the same engine
    faults.arm(f"serve_raise@{eng.stats['iterations']}")
    eng.submit(_prompts([6], cfg.vocab_size, seed=34)[0],
               max_new_tokens=4, temperature=0.0)
    with pytest.raises(faults.FaultInjected):
        eng.run()
    eng.reset_after_crash()
    eng.run()
    assert eng.compile_stats() == baseline  # zero new compiles


# -- graceful drain -----------------------------------------------------


def test_drain_completes_inflight_rejects_new_and_closes():
    cfg, params = _setup("control")
    client = ServingClient(ServingEngine(
        params, cfg, _serving(num_slots=2, drain_timeout_s=60),
    ))
    prompts = _prompts([5, 8, 4], cfg.vocab_size, seed=35)
    handles = [
        client.runner.submit(p, max_new_tokens=6, temperature=0.0)
        for p in prompts
    ]
    done = client.drain()
    assert done is True
    for p, h in zip(prompts, handles):  # zero lost in-flight requests
        assert h.done.is_set() and h.error is None
        assert h.result.tokens == _ref_greedy(params, cfg, p, 6)
    assert client.status() == "draining"
    with pytest.raises(ShuttingDownError):
        client.runner.submit(prompts[0], max_new_tokens=2)


def test_drain_budget_expiry_fails_stragglers_typed():
    """A drain that cannot finish in budget still never hangs anyone:
    leftovers get ShuttingDownError when the loop aborts."""

    class _NeverFinishes:
        def __init__(self):
            self.serving = ServingConfig(num_slots=1)
            self.stats = {"rejected": 0}
            self._q = []

        def queue_len(self):
            return len(self._q)

        def has_work(self):
            return bool(self._q)

        def submit(self, prompt, params=None):
            self._q.append(len(self._q))
            return len(self._q) - 1

        def cancel(self, rid):
            return False

        def step(self):
            time.sleep(0.005)
            return []

    runner = EngineRunner(_NeverFinishes())
    h = runner.submit([1], max_new_tokens=4)
    t0 = time.monotonic()
    assert runner.drain(timeout=0.3) is False
    assert time.monotonic() - t0 < 10
    assert h.done.wait(10)
    assert isinstance(h.error, ShuttingDownError)


def test_close_raises_on_stuck_engine_thread():
    """Satellite: close() must surface a thread that outlives its join
    timeout (wedged device call) instead of silently leaking it."""

    class _Stuck:
        def __init__(self):
            self.serving = ServingConfig(num_slots=1)
            self.stats = {"rejected": 0}
            self.release = threading.Event()
            self._q = []

        def queue_len(self):
            return len(self._q)

        def has_work(self):
            return bool(self._q)

        def submit(self, prompt, params=None):
            self._q.append(0)
            return 0

        def cancel(self, rid):
            return False

        def step(self):
            self.release.wait(30)  # a wedged device call
            self._q.clear()
            return []

    eng = _Stuck()
    runner = EngineRunner(eng)
    runner.submit([1], max_new_tokens=2)
    deadline = time.time() + 5
    while runner._step_started is None and time.time() < deadline:
        time.sleep(0.01)  # wait until the loop is inside step()
    with pytest.raises(RuntimeError, match="failed to stop"):
        runner.close(timeout=0.2)
    # a wedged engine reports FAILED, not a routine drain
    assert runner.status() == "failed"
    eng.release.set()  # unwedge so the daemon thread exits


# -- watchdog -----------------------------------------------------------

def test_watchdog_marks_degraded_then_recovers():
    class _Slow:
        def __init__(self):
            self.serving = ServingConfig(num_slots=1,
                                         step_time_budget_s=0.05)
            self.stats = {"rejected": 0}
            self._q = []
            self.durations = []
            self._rid = 0

        def queue_len(self):
            return len(self._q)

        def has_work(self):
            return bool(self._q)

        def submit(self, prompt, params=None):
            self._q.append(self._rid)
            self._rid += 1
            return self._rid - 1

        def cancel(self, rid):
            return False

        def step(self):
            if self.durations:
                time.sleep(self.durations.pop(0))
            if self._q:
                self._q.pop(0)
            return []  # requests never complete; irrelevant here

    eng = _Slow()
    runner = EngineRunner(eng)
    try:
        assert runner.status() == "healthy"
        eng.durations.append(0.4)  # 8x over budget
        runner.submit([1], max_new_tokens=2)
        deadline = time.time() + 10
        seen_degraded = False
        while time.time() < deadline:
            if runner.status() == "degraded":
                seen_degraded = True
                break
            time.sleep(0.005)
        assert seen_degraded  # flagged while (or right after) overrun
        eng.durations.append(0.0)
        runner.submit([1], max_new_tokens=2)  # a fast step clears it
        deadline = time.time() + 10
        while runner.status() != "healthy" and time.time() < deadline:
            time.sleep(0.005)
        assert runner.status() == "healthy"
        assert runner.last_step_s is not None
    finally:
        runner.close(timeout=10)


# -- HTTP surface -------------------------------------------------------


def test_http_health_ready_and_drain_503_with_retry_after():
    """/health carries status, /ready flips to 503 + Retry-After once
    draining, and /generate during drain is a typed 503."""
    cfg, params = _setup("control")
    client = ServingClient(ServingEngine(params, cfg, _serving()))
    httpd = serve(client, port=0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/health", timeout=30
        ) as r:
            health = json.load(r)
        assert health["ok"] is True
        assert health["status"] == "healthy"
        assert "deadline_expired" in health["stats"]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/ready", timeout=30
        ) as r:
            assert json.load(r)["ready"] is True

        assert client.drain(timeout=30) is True

        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/ready", timeout=30)
        assert ei.value.code == 503
        assert float(ei.value.headers["Retry-After"]) >= 1
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"prompt_ids": [1, 2],
                             "max_new_tokens": 2}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 503
        assert "Retry-After" in ei.value.headers
        # machine-readable error typing — what retry clients key off
        assert json.loads(ei.value.read())["code"] == "shutting_down"
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/health", timeout=30
        ) as r:
            health = json.load(r)
        assert health["ok"] is False and health["status"] == "draining"
    finally:
        httpd.shutdown()
        httpd.server_close()


# -- chaos (slow tier) --------------------------------------------------


@pytest.mark.slow
def test_chaos_engine_crash_under_concurrent_http_load():
    """Acceptance pin: a mid-batch engine crash under concurrent HTTP
    load -> every client gets a typed retriable failure or a successful
    retried response within its timeout (no hangs), and the restarted
    engine serves bit-identical greedy output for a fresh request."""
    cfg, params = _setup("control")
    client = ServingClient(ServingEngine(
        params, cfg,
        _serving(num_slots=2, max_restarts=3),
    ))
    httpd = serve(client, port=0)
    port = httpd.server_address[1]
    url = f"http://127.0.0.1:{port}/generate"
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    faults.arm("serve_raise@4")
    prompts = _prompts([5, 8, 3, 11, 6, 9], cfg.vocab_size, seed=36)
    results = [None] * len(prompts)

    def post(i):
        import random

        status, body, _r = http_post_json_with_retries(
            url, {"prompt_ids": prompts[i], "max_new_tokens": 8,
                  "temperature": 0.0, "timeout": 120},
            timeout=120, max_retries=4, base=0.05, cap=0.5,
            rng=random.Random(i),
        )
        results[i] = (status, body)

    try:
        threads = [
            threading.Thread(target=post, args=(i,))
            for i in range(len(prompts))
        ]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
            assert not t.is_alive(), "client hung after engine crash"
        assert time.monotonic() - t0 < 180
        n_ok = 0
        for i, (status, body) in enumerate(results):
            assert status in (200, 503), (i, status, body)
            if status == 200:
                n_ok += 1
                assert body["tokens"] == _ref_greedy(
                    params, cfg, prompts[i], 8
                )
        assert n_ok >= 1  # retries landed on the rebuilt engine
        assert client.stats["engine_restarts"] >= 1
        # fresh request on the restarted engine: bit-identical
        p = _prompts([7], cfg.vocab_size, seed=37)[0]
        out = client.generate(p, max_new_tokens=8, temperature=0.0,
                              timeout=120)
        assert out.tokens == _ref_greedy(params, cfg, p, 8)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/health", timeout=30
        ) as r:
            health = json.load(r)
        assert health["status"] == "healthy"
        assert health["restarts"] >= 1
    finally:
        httpd.shutdown()
        httpd.server_close()
        client.close()


@pytest.mark.slow
def test_chaos_drain_under_load_loses_nothing():
    """Acceptance pin: drain() under concurrent load -> new requests
    rejected 503 + Retry-After, every accepted request completes
    bit-identically, drain finishes inside its budget."""
    cfg, params = _setup("control")
    client = ServingClient(ServingEngine(
        params, cfg, _serving(num_slots=2, drain_timeout_s=120),
    ))
    httpd = serve(client, port=0)
    port = httpd.server_address[1]
    url = f"http://127.0.0.1:{port}/generate"
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    prompts = _prompts([9, 6, 12, 5, 8], cfg.vocab_size, seed=38)
    codes = [None] * len(prompts)
    bodies = [None] * len(prompts)

    def post(i):
        req = urllib.request.Request(
            url, data=json.dumps({
                "prompt_ids": prompts[i], "max_new_tokens": 16,
                "temperature": 0.0, "timeout": 120,
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=120) as r:
                codes[i], bodies[i] = r.status, json.load(r)
        except urllib.error.HTTPError as e:
            codes[i] = e.code
            bodies[i] = {"retry_after": e.headers.get("Retry-After")}

    try:
        threads = [
            threading.Thread(target=post, args=(i,))
            for i in range(len(prompts))
        ]
        for t in threads:
            t.start()
        # wait until the engine actually has the load in hand
        deadline = time.time() + 60
        while time.time() < deadline and (
            client.runner.engine.stats["iterations"] < 1
        ):
            time.sleep(0.005)
        t0 = time.monotonic()
        drained = client.drain()  # budget 120s
        drain_wall = time.monotonic() - t0
        assert drained is True
        assert drain_wall < 120
        # post-drain: a new request is a fast 503 with Retry-After
        late = urllib.request.Request(
            url, data=json.dumps({"prompt_ids": prompts[0],
                                  "max_new_tokens": 2}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(late, timeout=30)
        assert ei.value.code == 503
        assert "Retry-After" in ei.value.headers
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "request lost in drain"
        for i, code in enumerate(codes):
            # accepted -> completed bit-identically; the ones that hit
            # the drain window get the retriable 503
            assert code in (200, 503), (i, code, bodies[i])
            if code == 200:
                assert bodies[i]["tokens"] == _ref_greedy(
                    params, cfg, prompts[i], 16
                )
        assert codes.count(200) >= 1  # load was genuinely in flight
    finally:
        httpd.shutdown()
        httpd.server_close()


@pytest.mark.slow
def test_serve_bench_http_smoke_reports_error_breakdown():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "tools",
                      "serve_bench.py"),
         "--smoke", "--http"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["http"] is True
    assert line["n_requests"] == 8
    assert line["failed"] == 0
    assert set(line["errors"]) == {
        "queue_full", "engine_crash", "deadline", "timeout",
        "shutting_down", "other",
    }
