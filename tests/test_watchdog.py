"""Step-deadline watchdog tests (train/watchdog.py): unit behavior with
injected clock/exit, trainer integration (compile-count pin with
watchdog + heartbeat enabled), supervisor hang classification, and —
slow tier — THE chaos acceptance test: a supervised run wedged by
``train_hang`` is detected, restarted as class ``hang``, elastically
resumed on half the devices, and ends bit-identical to an uninterrupted
run at that mesh.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from differential_transformer_replication_tpu.train.watchdog import (
    HANG_EXIT_CODE,
    StepWatchdog,
    dump_hang_report,
    thread_stacks,
)
from differential_transformer_replication_tpu.utils import faults

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")
SUPERVISOR = os.path.join(TOOLS, "train_supervisor.py")
TRAIN_PY = os.path.join(os.path.dirname(__file__), "..", "train.py")


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class _Counter:
    def __init__(self):
        self.n = 0

    def inc(self, amount=1.0):
        self.n += amount


def _watchdog(tmp_path, budget=10.0, **kw):
    """A watchdog with no monitor thread (poll driven by check()), a
    fake clock, and a recording exit_fn — every fire path observable
    without killing pytest."""
    clock = kw.pop("clock", FakeClock())
    exits = []
    rows = []
    wd = StepWatchdog(
        budget,
        report_path=str(tmp_path / "hang_report.json"),
        sink=rows.append,
        fires_counter=kw.pop("fires_counter", _Counter()),
        clock=clock,
        exit_fn=exits.append,
        # huge poll so the monitor thread never races the fake clock;
        # tests drive expiry synchronously via check()
        poll_s=3600.0,
        **kw,
    )
    return wd, clock, exits, rows


class TestStepWatchdogUnit:
    def test_fires_on_expired_armed_deadline(self, tmp_path):
        wd, clock, exits, rows = _watchdog(tmp_path, budget=5.0)
        wd.arm(7)
        clock.t = 4.0
        wd.check()
        assert not wd.fired and exits == []
        clock.t = 6.0
        wd.check()
        assert wd.fired
        assert exits == [HANG_EXIT_CODE]
        report = json.load(open(tmp_path / "hang_report.json"))
        assert report["iter"] == 7
        assert report["record"] == "hang"
        assert "deadline" in report["reason"]
        # every live thread's stack is in the post-mortem, and the
        # metrics row carries the summary without the stacks
        assert any("MainThread" in k for k in report["threads"])
        assert rows and rows[0]["iter"] == 7
        assert "threads" not in rows[0]
        wd.close()

    def test_disarm_prevents_fire(self, tmp_path):
        wd, clock, exits, _ = _watchdog(tmp_path, budget=5.0)
        wd.arm(3)
        wd.disarm()
        clock.t = 100.0
        wd.check()
        assert not wd.fired and exits == []
        wd.close()

    def test_rearm_refreshes_deadline(self, tmp_path):
        wd, clock, exits, _ = _watchdog(tmp_path, budget=5.0)
        wd.arm(1)
        clock.t = 4.0
        wd.arm(2)  # next iteration: deadline moves to 9.0
        clock.t = 8.0
        wd.check()
        assert not wd.fired
        clock.t = 9.5
        wd.check()
        assert wd.fired and exits == [HANG_EXIT_CODE]
        wd.close()

    def test_trip_fires_even_disarmed(self, tmp_path):
        """The heartbeat mesh's coordinated abort: a dead peer trips
        the watchdog whatever the arming state (waiting for the local
        deadline inside a wedged collective only burns time)."""
        counter = _Counter()
        wd, clock, exits, rows = _watchdog(
            tmp_path, budget=0.0, fires_counter=counter
        )
        assert wd._thread is None  # budget 0: no monitor thread at all
        wd.trip("peer process 3 heartbeat silent for 11.0s")
        assert wd.fired and exits == [HANG_EXIT_CODE]
        assert counter.n == 1
        assert "peer process 3" in rows[0]["reason"]
        wd.close()

    def test_fires_at_most_once(self, tmp_path):
        wd, clock, exits, _ = _watchdog(tmp_path, budget=1.0)
        wd.arm(1)
        clock.t = 2.0
        wd.check()
        wd.trip("again")
        wd.check()
        assert exits == [HANG_EXIT_CODE]
        wd.close()

    def test_context_callables_land_in_report_and_errors_contained(
        self, tmp_path
    ):
        wd, clock, exits, _ = _watchdog(tmp_path, budget=1.0)
        wd.add_context(
            compile_events=lambda: 1,
            broken=lambda: 1 / 0,
        )
        wd.arm(4)
        clock.t = 5.0
        wd.check()
        report = json.load(open(tmp_path / "hang_report.json"))
        assert report["compile_events"] == 1
        assert "context error" in report["broken"]
        wd.close()

    def test_monitor_thread_fires_with_real_clock(self, tmp_path):
        """End-to-end on the real monitor thread: a tiny budget armed
        and never disarmed fires within a fraction of a second."""
        exits = []
        fired = threading.Event()

        def exit_fn(code):
            exits.append(code)
            fired.set()

        wd = StepWatchdog(
            0.05, report_path=str(tmp_path / "r.json"), exit_fn=exit_fn
        )
        wd.arm(1)
        assert fired.wait(timeout=5.0)
        assert exits == [HANG_EXIT_CODE]
        wd.close()

    def test_stuck_diagnostics_do_not_block_exit(self, tmp_path):
        """The likeliest pod hang IS stuck shared storage — which is
        where the report usually goes. A diagnostics path that blocks
        forever (simulated by a wedged context callable) must not
        wedge the fire: the exit lands within report_timeout_s."""
        wd, clock, exits, _ = _watchdog(tmp_path, budget=1.0,
                                        report_timeout_s=0.2)
        wd.add_context(stuck_mount=lambda: time.sleep(60))
        wd.arm(1)
        clock.t = 2.0
        t0 = time.perf_counter()
        wd.check()
        assert time.perf_counter() - t0 < 5.0
        assert exits == [HANG_EXIT_CODE]
        wd.close()

    def test_report_write_failure_does_not_block_exit(self, tmp_path):
        """Diagnostics are best-effort: an unwritable report path must
        not stop the exit that converts the hang into a restart."""
        wd, clock, exits, _ = _watchdog(tmp_path, budget=1.0)
        wd.report_path = "/proc/definitely/not/writable/r.json"
        wd.arm(1)
        clock.t = 2.0
        wd.check()
        assert exits == [HANG_EXIT_CODE]
        wd.close()


def test_thread_stacks_names_this_thread():
    stacks = thread_stacks()
    me = threading.current_thread().name
    assert me in stacks
    assert "test_thread_stacks_names_this_thread" in stacks[me]


def test_dump_hang_report_atomic_and_parseable(tmp_path):
    path = str(tmp_path / "sub" / "hang.json")
    report = dump_hang_report(path, 42, "test reason", 1.5,
                              context={"k": lambda: "v"})
    on_disk = json.load(open(path))
    assert on_disk["iter"] == 42 and on_disk["k"] == "v"
    assert report["reason"] == "test reason"
    assert not [f for f in os.listdir(tmp_path / "sub")
                if f.endswith(".tmp")]


class TestTrainStallFault:
    def test_train_hang_sleeps_and_disarms(self, monkeypatch):
        monkeypatch.setenv(faults.TRAIN_HANG_ENV_VAR, "0.12")
        faults.arm("train_hang@5")
        t0 = time.perf_counter()
        faults.train_stall(4)  # wrong iter: no stall
        assert time.perf_counter() - t0 < 0.05
        t0 = time.perf_counter()
        faults.train_stall(5)
        assert 0.1 <= time.perf_counter() - t0 < 1.0
        t0 = time.perf_counter()
        faults.train_stall(5)  # one-shot: disarmed
        assert time.perf_counter() - t0 < 0.05

    def test_collective_skew_uses_its_own_env(self, monkeypatch):
        monkeypatch.setenv(faults.SKEW_ENV_VAR, "0.1")
        monkeypatch.setenv(faults.TRAIN_HANG_ENV_VAR, "9.0")  # must NOT apply
        faults.arm("collective_skew@2")
        t0 = time.perf_counter()
        faults.train_stall(2)
        dt = time.perf_counter() - t0
        assert 0.08 <= dt < 1.0


class TestSupervisorHang:
    def _sup(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location("ts", SUPERVISOR)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_classify_hang_exit(self):
        sup = self._sup()
        assert sup.HANG_EXIT_CODE == HANG_EXIT_CODE
        assert sup.classify_exit(HANG_EXIT_CODE) == "hang"
        assert sup.classify_exit(1) == "crash"
        assert sup.classify_exit(-signal.SIGKILL) == "sigkill"

    def test_elastic_mesh_rewrite(self):
        sup = self._sup()
        cmd = ["python", "train.py", "--data-parallel", "8", "--seed", "1"]
        out = sup.with_elastic_mesh(cmd, 4)
        assert out == ["python", "train.py", "--seed", "1",
                       "--data-parallel", "4"]
        # non-data axes survive and scale the data axis down
        cmd2 = ["t", "--data-parallel=4", "--tensor-parallel", "2"]
        assert sup.with_elastic_mesh(cmd2, 4) == [
            "t", "--tensor-parallel", "2", "--data-parallel", "2"
        ]
        # non-data axes alone exceeding the devices: untouched (the
        # child fails loudly rather than silently retopologizing)
        cmd3 = ["t", "--tensor-parallel", "8"]
        assert sup.with_elastic_mesh(cmd3, 4) == cmd3
        # already right-sized: untouched
        cmd4 = ["t", "--data-parallel", "4"]
        assert sup.with_elastic_mesh(cmd4, 4) == cmd4
        # shrink-only: a deliberately under-subscribed mesh (dp 4 on 8
        # surviving devices) is NEVER upsized by a restart
        cmd5 = ["t", "--data-parallel", "4"]
        assert sup.with_elastic_mesh(cmd5, 8) == cmd5

    def test_probe_device_count_runs_command(self):
        sup = self._sup()
        n = sup.probe_device_count([sys.executable, "-c", "print(4)"])
        assert n == 4
        assert sup.probe_device_count(
            [sys.executable, "-c", "print('nope')"]
        ) is None

    def test_hang_budget_separate_from_crash_budget(self, tmp_path):
        """A child that hangs (exit 113) twice then succeeds restarts
        under --max-hang-restarts even with --max-restarts 0: the two
        budgets are independent."""
        script = tmp_path / "hangy.py"
        script.write_text(
            "import os, sys\n"
            f"p = {str(tmp_path / 'count')!r}\n"
            "n = int(open(p).read()) if os.path.exists(p) else 0\n"
            "open(p, 'w').write(str(n + 1))\n"
            f"sys.exit(0 if n >= 2 else {HANG_EXIT_CODE})\n"
        )
        log = tmp_path / "restarts.json"
        proc = subprocess.run(
            [sys.executable, SUPERVISOR, "--backoff-base", "0.01",
             "--restart-log", str(log), "--max-restarts", "0",
             "--max-hang-restarts", "3", "--",
             sys.executable, str(script)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        records = [json.loads(l) for l in open(log)]
        assert [r["outcome"] for r in records] == ["hang", "hang", "clean"]

    def test_hang_budget_exhausts_independently(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, SUPERVISOR, "--backoff-base", "0.01",
             "--max-restarts", "5", "--max-hang-restarts", "1", "--",
             sys.executable, "-c", f"import sys; sys.exit({HANG_EXIT_CODE})"],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == HANG_EXIT_CODE
        assert "hang restart budget exhausted" in proc.stderr


class TestTrainerIntegration:
    def test_watchdog_and_heartbeat_add_no_recompiles(self, tmp_path):
        """Acceptance pin: watchdog + heartbeat are pure host-side
        threads — a run with both enabled (and a generous deadline)
        completes, compiles exactly once, never fires, and leaves its
        heartbeat record behind."""
        import json as _json

        from differential_transformer_replication_tpu.config import (
            ModelConfig,
            TrainConfig,
        )
        from differential_transformer_replication_tpu.train import train

        cfg = TrainConfig(
            model=ModelConfig(model="diff", vocab_size=256, n_embd=32,
                              n_head=2, n_layer=2, block_size=16,
                              dropout=0.0, compute_dtype="float32"),
            vocab_size=256, dataset="synthetic", num_train_samples=200,
            micro_batch_size=4, grad_acc_steps=1, max_iters=12,
            eval_interval=6, eval_iters=2, log_interval=2,
            warmup_iters=5, control_head_multiplier=1,
            tokenizer_dir=str(tmp_path / "tok"),
            checkpoint_path=str(tmp_path / "best"),
            last_checkpoint_path=str(tmp_path / "last"),
            metrics_path=str(tmp_path / "m.jsonl"),
            seed=7,
            step_deadline_s=120.0,
            heartbeat_dir=str(tmp_path / "hb"),
            heartbeat_interval_s=0.05, heartbeat_timeout_s=5.0,
        )
        state = train(cfg)
        assert int(state["step"]) == 12
        recs = [_json.loads(l) for l in open(cfg.metrics_path)]
        pins = [r["compile_events"] for r in recs if "compile_events" in r]
        assert pins and set(pins) == {1}
        assert not [r for r in recs if r.get("record") == "hang"]
        hb = _json.load(open(tmp_path / "hb" / "hb-0.json"))
        assert hb["process_index"] == 0 and hb["seq"] >= 1
        assert not os.path.exists(str(tmp_path / "best.hang_report.json"))


# -- chaos (slow tier) --------------------------------------------------


def _train_env(extra_faults=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env.pop(faults.ENV_VAR, None)
    if extra_faults:
        env[faults.ENV_VAR] = extra_faults
    return env


def _train_cmd(tmp_path, name, *extra):
    d = tmp_path / name
    d.mkdir(exist_ok=True)
    return d, [
        sys.executable, TRAIN_PY, "--model", "diff",
        "--dataset", "synthetic", "--num-train-samples", "200",
        "--vocab-size", "256", "--n-embd", "32", "--n-head", "2",
        "--n-layer", "2", "--block-size", "16",
        "--compute-dtype", "float32", "--micro-batch-size", "8",
        "--max-iters", "24", "--eval-interval", "100", "--eval-iters", "2",
        "--learning-rate", "3e-3", "--warmup-iters", "5", "--seed", "7",
        "--tokenizer-dir", str(tmp_path / "tokenizer"),
        "--checkpoint-path", str(d / "best.ckpt"),
        "--last-checkpoint-path", str(d / "last.ckpt"),
        "--metrics-path", str(d / "metrics.jsonl"),
        *extra,
    ]


@pytest.mark.slow
def test_chaos_train_hang_watchdog_elastic_resume(tmp_path):
    """THE resilience acceptance test, end to end: a supervised dp=8
    run wedges mid-step (train_hang) -> the step-deadline watchdog
    fires (no infinite hang), dumps hang_report.json and exits with the
    hang code -> the supervisor classifies ``hang``, probes the
    surviving device count (halved to 4 via --elastic-probe), rewrites
    --data-parallel, and relaunches with --resume-from auto -> the
    relaunch elastically reshards the dp-8 step checkpoint onto the
    dp-4 mesh and finishes cleanly. The final state is bit-identical to
    an uninterrupted dp-4 run resumed from the same checkpoint, and
    compile_events stays 1 with watchdog + heartbeat enabled."""
    chaos_dir, cmd = _train_cmd(
        tmp_path, "chaos",
        "--data-parallel", "8",
        "--ckpt-interval", "8", "--ckpt-keep-last", "8",
        "--step-deadline-s", "2.0",
        "--heartbeat-dir", str(tmp_path / "chaos" / "hb"),
        "--resume-from", "auto",
    )
    env = _train_env("train_hang@16")
    env[faults.TRAIN_HANG_ENV_VAR] = "120"  # far beyond the deadline
    log = chaos_dir / "restarts.json"
    proc = subprocess.run(
        [sys.executable, SUPERVISOR, "--backoff-base", "0.05",
         "--max-restarts", "0", "--max-hang-restarts", "2",
         "--restart-log", str(log),
         "--elastic", "--elastic-probe", f"{sys.executable} -c print(4)",
         "--"] + cmd,
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    records = [json.loads(l) for l in open(log)]
    # wedged once, classified hang (not crash), restarted elastically
    assert [r["outcome"] for r in records] == ["hang", "clean"]
    assert records[0]["rc"] == HANG_EXIT_CODE
    assert records[1]["elastic_devices"] == 4
    assert "--data-parallel 4" in " ".join(records[1]["argv"])
    # the watchdog's post-mortem names the wedged iteration and has
    # the main thread's stack
    report = json.load(open(chaos_dir / "best.hang_report.json"))
    assert report["iter"] == 16
    assert report["threads"]
    # the relaunch resumed from the certified step-16 checkpoint
    assert "Resumed from" in proc.stdout
    assert "[elastic] resuming" in proc.stdout

    # control: an uninterrupted dp-4 run resumed from the SAME step-16
    # checkpoint must end bit-identical (elastic reshard is lossless
    # and the consumed-window fast-forward is exact)
    step_ckpt = str(chaos_dir / "best.steps" / "step-00000016")
    assert os.path.isdir(step_ckpt)
    _, control_cmd = _train_cmd(
        tmp_path, "control",
        "--data-parallel", "4",
        "--resume-from", step_ckpt,
    )
    proc_c = subprocess.run(control_cmd, capture_output=True, text=True,
                            timeout=600, env=_train_env())
    assert proc_c.returncode == 0, proc_c.stderr[-2000:]
    sa = open(chaos_dir / "last.ckpt" / "state.msgpack", "rb").read()
    sb = open(tmp_path / "control" / "last.ckpt" / "state.msgpack",
              "rb").read()
    assert sa == sb

    # compile pin: watchdog + heartbeat are pure host threads — the
    # relaunched (watchdog-enabled) incarnation still compiles once
    lines = [json.loads(l) for l in open(chaos_dir / "metrics.jsonl")]
    compile_counts = [l["compile_events"] for l in lines
                      if "compile_events" in l]
    assert compile_counts and set(compile_counts) == {1}
