"""Test configuration: run everything on a virtual 8-device CPU mesh.

This is the TPU-world stand-in for a multi-chip testbed (SURVEY.md
section 4): ``xla_force_host_platform_device_count`` fakes 8 devices so
sharding/collective tests run on one host. Must be set before jax is
imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The container's sitecustomize imports jax at interpreter start (before
# this conftest), so the env var alone is too late — force the platform
# through the live config as well. Backends must not have initialized yet.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

assert jax.default_backend() == "cpu", "tests must run on the virtual CPU mesh"
assert jax.device_count() == 8, "expected 8 virtual CPU devices for sharding tests"

import pytest  # noqa: E402

# Test tiers (VERDICT r1 item 8): ``pytest -m quick`` is the <3-minute
# smoke pass; the default (no -m) runs everything (~23 min on an 8-core
# host, dominated by interpreter-mode Pallas parity and end-to-end
# trainer tests). Membership is by nodeid substring: the patterns below
# name the measured-slow tests/classes/modules (--durations=40 run,
# 2026-07-30); everything else is marked quick.
_SLOW_PATTERNS = (
    "test_multihost_2proc.py",
    "test_pipeline.py",
    "test_remat.py",
    "test_runtime.py::TestEndToEnd",
    "test_parallel.py::TestShardedStep",
    "test_parallel.py::TestShardedTraining",
    "test_parallel.py::TestShardFlash",
    "test_decode.py",
    "test_flash_models.py",
    "test_train.py::TestTrainStep::test_loss_decreases_all_models",
    "test_train.py::TestTrainStep::test_grad_accumulation_matches_big_batch",
    "test_ring.py::test_sharded_train_step_with_sequence_axis",
    "test_ring.py::test_ring_flash",
    "test_losses.py::TestModelLossChunk",
    "test_models.py::TestInitAndShapes::test_init_statistics",
    "test_flash.py::test_ndiff_grad_parity",
    "test_flash.py::test_diff_grad_parity",
    "test_flash.py::test_vjp",
    "test_torch_import.py",
    "test_torch_export.py",
    # ulysses: the model-forward/train-step/dropout tests are slow; the
    # bare-op parity tests (diff/ndiff/tensor-axis/uneven-heads, each a
    # few seconds) stay in the quick smoke pass
    "test_ulysses.py::test_ulysses_train_step",
    "test_ulysses.py::test_model_forward_ulysses",
    "test_ulysses.py::test_ulysses_pallas_dropout",
    "test_ulysses.py::test_ulysses_dropout",
    "test_ulysses.py::test_ulysses_grad_parity",
    "test_ulysses.py::test_vanilla_ulysses_parity",
    "test_flash_dropout.py::test_grad_matches_dense_with_same_masks",
    "test_flash_dropout.py::test_tiled_kernels_match_dense_with_same_masks",
    "test_flash_dropout.py::test_model_forward_with_fused_dropout",
    "test_ring.py::TestRingDropout::test_mean_preservation",
    "test_ring.py::TestRingDropout::test_grads_flow",
)


def pytest_collection_modifyitems(config, items):
    for item in items:
        # explicit @pytest.mark.slow decorators (e.g. the multi-second
        # serving tests, test_serving.py) count like pattern membership
        if any(pat in item.nodeid for pat in _SLOW_PATTERNS):
            item.add_marker(pytest.mark.slow)
        elif item.get_closest_marker("slow") is None:
            item.add_marker(pytest.mark.quick)
