"""Test configuration: run everything on a virtual 8-device CPU mesh.

This is the TPU-world stand-in for a multi-chip testbed (SURVEY.md
section 4): ``xla_force_host_platform_device_count`` fakes 8 devices so
sharding/collective tests run on one host. Must be set before jax is
imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The container's sitecustomize imports jax at interpreter start (before
# this conftest), so the env var alone is too late — force the platform
# through the live config as well. Backends must not have initialized yet.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)

assert jax.default_backend() == "cpu", "tests must run on the virtual CPU mesh"
assert jax.device_count() == 8, "expected 8 virtual CPU devices for sharding tests"
