"""Tests for the native data-pipeline library (native/src/data_native.cpp
via data/native.py): the Feistel epoch permutation and the threaded host
window gather, plus C++ <-> numpy fallback parity."""

import numpy as np
import pytest

from differential_transformer_replication_tpu.data import native
from differential_transformer_replication_tpu.data.native import (
    EpochPermutation,
    _permute_np,
    gather_windows,
    native_available,
    permute_indices,
)


@pytest.mark.parametrize("n", [1, 2, 7, 100, 1000, 12_345])
def test_permutation_is_bijective(n):
    out = permute_indices(n, seed=42, start=0, count=n)
    assert sorted(out.tolist()) == list(range(n))


def test_permutation_windows_compose():
    """Streaming the permutation in chunks equals taking it whole."""
    n = 5000
    whole = permute_indices(n, seed=7, start=0, count=n)
    parts = np.concatenate(
        [permute_indices(n, seed=7, start=s, count=1000) for s in range(0, n, 1000)]
    )
    np.testing.assert_array_equal(parts, whole)


def test_different_seeds_differ():
    n = 4096
    a = permute_indices(n, seed=1, start=0, count=n)
    b = permute_indices(n, seed=2, start=0, count=n)
    assert not np.array_equal(a, b)


@pytest.mark.skipif(not native_available(), reason="no C++ toolchain")
@pytest.mark.parametrize("n", [3, 257, 10_000])
def test_cpp_matches_numpy(n):
    """The ctypes path and the numpy fallback implement the identical
    bijection, so behavior cannot depend on toolchain availability."""
    got = permute_indices(n, seed=99, start=0, count=n)  # C++ path
    ref = _permute_np(n, seed=99, start=0, count=n)
    np.testing.assert_array_equal(got, ref)


def test_gather_windows_semantics():
    tokens = np.arange(100, dtype=np.int32)
    offs = np.array([0, 5, 90], np.int64)
    out = gather_windows(tokens, offs, block=8)
    np.testing.assert_array_equal(out["x"][0], np.arange(8))
    np.testing.assert_array_equal(out["y"][0], np.arange(1, 9))
    np.testing.assert_array_equal(out["x"][2], np.arange(90, 98))
    np.testing.assert_array_equal(out["y"][2], np.arange(91, 99))


def test_gather_windows_bounds_check():
    tokens = np.arange(20, dtype=np.int32)
    with pytest.raises(ValueError):
        gather_windows(tokens, np.array([15], np.int64), block=8)


def test_epoch_permutation_exact_epochs():
    """Every index exactly once per epoch; epochs reshuffle; streaming
    across an epoch boundary works."""
    n = 103
    p = EpochPermutation(n, seed=5)
    first = p.take(n)
    assert sorted(first.tolist()) == list(range(n))
    assert p.epoch == 1 and p.cursor == 0
    # crossing the boundary: 2nd epoch's head differs from the 1st's
    second = p.take(n)
    assert sorted(second.tolist()) == list(range(n))
    assert not np.array_equal(first, second)
    # uneven take spanning epochs
    p2 = EpochPermutation(n, seed=5)
    chunks = np.concatenate([p2.take(40) for _ in range(6)])  # 240 = 2n + 34
    assert sorted(chunks[:n].tolist()) == list(range(n))
    assert sorted(chunks[n : 2 * n].tolist()) == list(range(n))
    np.testing.assert_array_equal(chunks[:n], first)


def test_native_reports_availability():
    # in this image g++ is baked in, so the native path should build;
    # if it ever can't, the numpy fallback keeps everything above passing
    assert isinstance(native_available(), bool)
