"""All-to-all (Ulysses) sequence-parallelism tests on the virtual CPU
mesh (parallel/ulysses.py) — the second context-parallel strategy beside
the ring. The all-to-all path must match the dense single-device ops up
to fp32 accumulation order, including gradients through both collectives
and full-model forwards/train steps with ``sequence_impl='ulysses'``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from differential_transformer_replication_tpu.config import (
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from differential_transformer_replication_tpu.models import init_model, model_forward
from differential_transformer_replication_tpu.ops import (
    causal_mask,
    diff_attention,
    ndiff_attention,
    ndiff_signs,
    vanilla_attention,
)
from differential_transformer_replication_tpu.ops.streams import (
    diff_coeffs,
    ndiff_coeffs,
    vanilla_coeffs,
)
from differential_transformer_replication_tpu.parallel import create_mesh
from differential_transformer_replication_tpu.parallel.ulysses import (
    ulysses_multi_stream_attention,
)

B, T, H, D = 2, 64, 4, 16


def _seq_mesh(n_seq: int, tensor: int = 1) -> Mesh:
    return create_mesh(MeshConfig(data=1, fsdp=1, tensor=tensor, sequence=n_seq))


def _rand(key, *shape):
    return jax.random.normal(key, shape, jnp.float32)


@pytest.mark.parametrize("n_seq", [2, 4])
def test_vanilla_ulysses_parity(n_seq):
    mesh = _seq_mesh(n_seq)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (_rand(kk, B, T, H, D) for kk in ks)
    ref = vanilla_attention(q, k, v, mask=causal_mask(T))
    got = jax.jit(
        lambda q, k, v: ulysses_multi_stream_attention(
            q[None], k[None], v, vanilla_coeffs(H), mesh
        )
    )(q, k, v)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_diff_ulysses_parity():
    mesh = _seq_mesh(4)
    ks = jax.random.split(jax.random.PRNGKey(1), 5)
    q1, k1, q2, k2 = (_rand(kk, B, T, H, D) for kk in ks[:4])
    v = _rand(ks[4], B, T, H, 2 * D)
    lam = jnp.full((H,), 0.37, jnp.float32)
    ref = diff_attention(q1, k1, q2, k2, v, lam, mask=causal_mask(T))
    got = jax.jit(
        lambda *a: ulysses_multi_stream_attention(
            jnp.stack([a[0], a[2]]), jnp.stack([a[1], a[3]]), a[4],
            diff_coeffs(lam), mesh,
        )
    )(q1, k1, q2, k2, v)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_ndiff_ulysses_parity():
    mesh = _seq_mesh(2)
    n = 3
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    qs = _rand(ks[0], n, B, T, H, D)
    kss = _rand(ks[1], n, B, T, H, D)
    v = _rand(ks[2], B, T, H, 2 * D)
    lams = jnp.abs(_rand(jax.random.PRNGKey(3), n, H)) * 0.3 + 0.1
    signs = ndiff_signs(n)
    ref = ndiff_attention(qs, kss, v, lams, signs, mask=causal_mask(T))
    got = jax.jit(
        lambda qs, kss, v: ulysses_multi_stream_attention(
            qs, kss, v, ndiff_coeffs(lams, signs), mesh
        )
    )(qs, kss, v)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_ulysses_grad_parity():
    """Gradients flow through BOTH all-to-alls (their transpose is the
    reverse all-to-all) and match dense autodiff."""
    mesh = _seq_mesh(4)
    ks = jax.random.split(jax.random.PRNGKey(4), 5)
    q1, k1, q2, k2 = (_rand(kk, B, T, H, D) for kk in ks[:4])
    v = _rand(ks[4], B, T, H, 2 * D)
    lam = jnp.full((H,), 0.2, jnp.float32)

    def loss_ref(q1, k1, q2, k2, v):
        out = diff_attention(q1, k1, q2, k2, v, lam, mask=causal_mask(T))
        return jnp.sum(out * jnp.cos(out))

    def loss_uly(q1, k1, q2, k2, v):
        out = ulysses_multi_stream_attention(
            jnp.stack([q1, q2]), jnp.stack([k1, k2]), v,
            diff_coeffs(lam), mesh,
        )
        return jnp.sum(out * jnp.cos(out))

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(q1, k1, q2, k2, v)
    g_got = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2, 3, 4)))(
        q1, k1, q2, k2, v
    )
    for r, g in zip(g_ref, g_got):
        np.testing.assert_allclose(g, r, rtol=1e-4, atol=1e-4)


def test_ulysses_composes_with_tensor_axis():
    """tensor=2 x sequence=2: heads shard over tensor first, then the
    all-to-all splits each tensor shard's heads across sequence."""
    mesh = _seq_mesh(2, tensor=2)
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    q, k, v = (_rand(kk, B, T, H, D) for kk in ks)
    ref = vanilla_attention(q, k, v, mask=causal_mask(T))
    got = jax.jit(
        lambda q, k, v: ulysses_multi_stream_attention(
            q[None], k[None], v, vanilla_coeffs(H), mesh
        )
    )(q, k, v)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_uneven_heads_fail_loudly():
    mesh = _seq_mesh(8)  # 4 heads over 8 sequence shards
    ks = jax.random.split(jax.random.PRNGKey(6), 3)
    q, k, v = (_rand(kk, B, T, H, D) for kk in ks)
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(
            lambda q, k, v: ulysses_multi_stream_attention(
                q[None], k[None], v, vanilla_coeffs(H), mesh
            )
        )(q, k, v)


@pytest.mark.parametrize("kind", ["control", "diff", "ndiff"])
def test_model_forward_ulysses(kind):
    """Full model forward with sequence_impl='ulysses' matches the dense
    forward — the dispatch routes through the all-to-all path."""
    mesh = _seq_mesh(4)
    cfg = ModelConfig(
        model=kind, vocab_size=97, n_embd=64, n_head=4, n_layer=2,
        block_size=32, dropout=0.0, n_terms=2, compute_dtype="float32",
        sequence_impl="ulysses",
    )
    params = init_model(jax.random.PRNGKey(0), cfg)
    idx = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)
    ref, _ = model_forward(params, idx, cfg)
    got, _ = jax.jit(lambda p, i: model_forward(p, i, cfg, mesh=mesh))(params, idx)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_ulysses_pallas_parity():
    """impl='pallas' inside the all-to-all body: the unmodified aligned-
    causal flash kernel runs on the full-T head slice (interpret mode on
    CPU)."""
    mesh = _seq_mesh(2)
    ks = jax.random.split(jax.random.PRNGKey(7), 5)
    q1, k1, q2, k2 = (_rand(kk, B, T, H, D) for kk in ks[:4])
    v = _rand(ks[4], B, T, H, 2 * D)
    lam = jnp.full((H,), 0.4, jnp.float32)
    ref = diff_attention(q1, k1, q2, k2, v, lam, mask=causal_mask(T))
    got = jax.jit(
        lambda *a: ulysses_multi_stream_attention(
            jnp.stack([a[0], a[2]]), jnp.stack([a[1], a[3]]), a[4],
            diff_coeffs(lam), mesh, "pallas",
        )
    )(q1, k1, q2, k2, v)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_ulysses_dropout():
    """Dropout on the all-to-all path: deterministic per key, distinct
    across keys, inert without one, grads finite."""
    mesh = _seq_mesh(2)
    ks = jax.random.split(jax.random.PRNGKey(8), 3)
    q, k, v = (_rand(kk, B, T, H, D) for kk in ks)

    def run(rng):
        return jax.jit(
            lambda q, k, v: ulysses_multi_stream_attention(
                q[None], k[None], v, vanilla_coeffs(H), mesh,
                dropout_rate=0.3, dropout_rng=rng,
            )
        )(q, k, v)

    a = run(jax.random.PRNGKey(2))
    b = run(jax.random.PRNGKey(2))
    c = run(jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.allclose(np.asarray(a), np.asarray(c))

    def loss(q, k, v):
        out = ulysses_multi_stream_attention(
            q[None], k[None], v, vanilla_coeffs(H), mesh,
            dropout_rate=0.3, dropout_rng=jax.random.PRNGKey(2),
        )
        return jnp.sum(out ** 2)

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    for arr in g:
        assert bool(jnp.all(jnp.isfinite(arr)))


def test_ulysses_pallas_dropout():
    """Kernel dropout under the all-to-all re-sharding: the per-shard rng
    fold must keep masks independent across sequence shards even though
    the kernel keys them on LOCAL (b*h) indices that repeat per shard.
    The sharp check: with IDENTICAL content in every head, the two
    sequence shards (each holding a head group) see byte-identical
    kernel inputs and identical local indices — so equal outputs across
    head groups would mean the masks repeated, i.e. the fold was lost."""
    mesh = _seq_mesh(2)
    k = jax.random.PRNGKey(9)
    one_head = _rand(k, B, T, 1, D)
    q = jnp.broadcast_to(one_head, (B, T, H, D))  # all H heads identical

    def run(rng):
        return jax.jit(
            lambda q: ulysses_multi_stream_attention(
                q[None], q[None], q, vanilla_coeffs(H), mesh, "pallas",
                dropout_rate=0.4, dropout_rng=rng,
            )
        )(q)

    a = run(jax.random.PRNGKey(2))
    b = run(jax.random.PRNGKey(2))
    c = run(jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.allclose(np.asarray(a), np.asarray(c))
    out = np.asarray(a)
    # shard 0 holds heads 0..1, shard 1 holds heads 2..3 (identical
    # inputs); without the fold their masks — hence outputs — coincide
    assert not np.allclose(out[:, :, : H // 2], out[:, :, H // 2 :]), (
        "sequence shards produced identical dropped outputs on identical "
        "inputs — per-shard rng fold lost"
    )
    # within one shard, mask independence across its two heads comes from
    # the kernel's own (b*h) keying: also must differ
    assert not np.allclose(out[:, :, 0], out[:, :, 1])


def test_ulysses_train_step():
    """End-to-end sharded train step with sequence_impl='ulysses' on a
    data=2 x sequence=2 x tensor=2 mesh."""
    from differential_transformer_replication_tpu.parallel import (
        make_sharded_train_step,
    )
    from differential_transformer_replication_tpu.parallel.dp_step import (
        create_sharded_train_state,
    )

    mesh_cfg = MeshConfig(data=2, fsdp=1, tensor=2, sequence=2)
    model = ModelConfig(
        model="diff", vocab_size=64, n_embd=64, n_head=4, n_layer=2,
        block_size=32, dropout=0.0, compute_dtype="float32",
        sequence_impl="ulysses",
    )
    cfg = TrainConfig(
        model=model, mesh=mesh_cfg, vocab_size=64, micro_batch_size=4,
        grad_acc_steps=2, control_head_multiplier=1,
    )
    mesh = create_mesh(mesh_cfg)
    state = create_sharded_train_state(jax.random.PRNGKey(0), cfg, mesh)
    step = make_sharded_train_step(cfg, mesh, state)
    x = jax.random.randint(jax.random.PRNGKey(1), (2, 4, 32), 0, 64)
    batch = {"x": x, "y": jnp.roll(x, -1, axis=-1)}
    state2, metrics = step(state, batch)
    assert jnp.isfinite(float(metrics["loss"]))
    assert int(state2["step"]) == 1
