"""Graceful degradation under KV pressure (PR 17).

The load-bearing contracts:

- **Host tier**: evicted radix pages demote into a byte-budgeted,
  checksummed host-RAM store (serving/host_tier.py) and promote back by
  copy — revisiting a demoted prefix is bit-exact with recompute and
  never recompiles; every tier fault (demote failure, promote hang,
  corrupted swap) degrades to a counted, typed recompute fallback.
- **Mid-decode preemption**: a low-priority request's live pages stash
  out to the host tier under page pressure and the request resumes
  BIT-EXACT after swap-in — greedy and sampled alike (per-request
  ``fold_in`` key chains), with the decode compile count pinned at 1.
- **Priority classes**: ``SamplingParams.priority`` orders admission
  (high < normal < batch), per-class slot bounds cap each class, and
  anti-starvation aging provably promotes a starved batch request over
  fresh high-priority traffic.
- **Shed honesty**: pool exhaustion sheds with a Retry-After derived
  from the observed page drain rate, and per-class TTFT/ITL histograms
  feed per-class SLO burn rates.
"""

import time
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from differential_transformer_replication_tpu.analysis.sanitizers import (
    RecompileSentinel,
)
from differential_transformer_replication_tpu.config import (
    ModelConfig,
    ServingConfig,
)
from differential_transformer_replication_tpu.models import (
    generate_cached,
    init_model,
)
from differential_transformer_replication_tpu.obs.slo import (
    SLOMonitor,
    default_serving_objectives,
)
from differential_transformer_replication_tpu.serving import (
    HostTier,
    PagePool,
    SamplingParams,
    Scheduler,
    ServingEngine,
)
from differential_transformer_replication_tpu.serving.host_tier import (
    payload_nbytes,
)
from differential_transformer_replication_tpu.utils import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _cfg(kind, **kw):
    base = dict(
        model=kind, vocab_size=61, n_embd=32, n_head=2, n_layer=2,
        block_size=32, dropout=0.0, n_terms=3, compute_dtype="float32",
    )
    base.update(kw)
    return ModelConfig(**base)


@lru_cache(maxsize=None)
def _setup(kind):
    cfg = _cfg(kind)
    return cfg, init_model(jax.random.PRNGKey(0), cfg)


def _prompts(lens, vocab, seed=1):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=L).tolist() for L in lens]


def _ref_greedy(params, cfg, prompt, n):
    out = generate_cached(
        params, jnp.asarray(prompt, jnp.int32)[None], cfg, n,
        jax.random.PRNGKey(0), temperature=0.0,
    )
    return np.asarray(out)[0, len(prompt):].tolist()


def _tiered(**kw):
    """Paged + host-tiered serving config; block 32 / page 8 gives
    4 pages per slot, so small pools create real KV pressure."""
    base = dict(num_slots=2, prefill_chunk=4, prefill_budget=6,
                kv_page_size=8, kv_pool_pages=6,
                host_tier_bytes=1 << 30)
    base.update(kw)
    return ServingConfig(**base)


def _payload(n=64, layers=2, seed=0):
    """A fake page image: per-layer dicts of byte arrays
    (2 * layers * n bytes total)."""
    rng = np.random.default_rng(seed)
    return [
        {"k": rng.integers(0, 255, n, dtype=np.uint8),
         "v": rng.integers(0, 255, n, dtype=np.uint8)}
        for _ in range(layers)
    ]


# ---------------------------------------------------------------------------
# HostTier unit tests (pure host state, no device work)
# ---------------------------------------------------------------------------


class TestHostTier:
    def test_put_get_roundtrip(self):
        tier = HostTier(budget_bytes=10_000)
        p = _payload(seed=1)
        assert tier.put(("a",), p)
        ent = tier.get(("a",))
        assert ent is not None and ent.verify()
        for got, want in zip(ent.payload, p):
            np.testing.assert_array_equal(got["k"], want["k"])
            np.testing.assert_array_equal(got["v"], want["v"])
        assert tier.get(("zz",)) is None
        st = tier.stats()
        assert st["hits_total"] == 1 and st["misses_total"] == 1
        assert st["entries"] == 1
        assert st["bytes"] == payload_nbytes(p)

    def test_budget_must_be_positive(self):
        with pytest.raises(ValueError):
            HostTier(budget_bytes=0)

    def test_lru_eviction_respects_recency(self):
        # each payload is 256 bytes; a 600-byte budget holds two
        tier = HostTier(budget_bytes=600)
        tier.put(("a",), _payload(seed=1))
        tier.put(("b",), _payload(seed=2))
        assert tier.get(("a",)) is not None  # refresh: b is now LRU
        tier.put(("c",), _payload(seed=3))
        assert tier.get(("b",)) is None
        assert tier.get(("a",)) is not None
        assert tier.get(("c",)) is not None
        assert tier.stats()["evictions_total"] == 1

    def test_reject_payload_over_budget(self):
        tier = HostTier(budget_bytes=100)
        assert not tier.put(("a",), _payload(seed=1))  # 256 > 100
        st = tier.stats()
        assert st["rejected_total"] == 1 and st["entries"] == 0

    def test_corruption_reads_as_counted_miss(self):
        tier = HostTier(budget_bytes=10_000)
        p = _payload(seed=4)
        tier.put(("a",), p)
        p[0]["k"][0] ^= 0xFF  # torn host copy: payload held by reference
        assert tier.get(("a",)) is None
        st = tier.stats()
        assert st["corrupt_total"] == 1 and st["entries"] == 0
        assert st["misses_total"] == 1 and st["hits_total"] == 0

    def test_stash_is_pinned_and_never_refused(self):
        tier = HostTier(budget_bytes=600)
        tier.put(("a",), _payload(seed=1))
        tier.put(("b",), _payload(seed=2))
        # a stash evicts cached entries to fit, never gets refused...
        tier.stash("req1", [_payload(seed=3), _payload(seed=4)])
        st = tier.stats()
        assert st["stashes"] == 1 and st["stash_bytes"] == 512
        assert st["entries"] <= 1  # cached made way
        # ...and a burst may overshoot the budget outright
        tier.stash("req2", [_payload(n=512, seed=5)])
        assert tier.stats()["bytes"] > 600
        ents = tier.unstash("req1")
        assert ents is not None and len(ents) == 2
        assert all(e.verify() for e in ents)
        assert tier.unstash("req1") is None
        tier.drop_stash("req2")
        assert tier.stats()["stash_bytes"] == 0

    def test_clear_cache_keeps_stashes_and_counters(self):
        tier = HostTier(budget_bytes=10_000)
        tier.put(("a",), _payload(seed=1))
        assert tier.get(("a",)) is not None
        tier.stash("req", [_payload(seed=2)])
        tier.clear_cache()
        st = tier.stats()
        assert st["entries"] == 0 and st["cached_bytes"] == 0
        assert st["stashes"] == 1 and st["stash_bytes"] > 0
        assert st["hits_total"] == 1  # monotonic counters survive


# ---------------------------------------------------------------------------
# Priority plumbing: params, config, rank math, queue depths
# ---------------------------------------------------------------------------


class TestPriorityPlumbing:
    def test_invalid_priority_rejected(self):
        with pytest.raises(ValueError):
            SamplingParams(priority="urgent")

    def test_slot_bounds_parse(self):
        sv = ServingConfig(num_slots=2, priority_max_slots="batch:1")
        assert sv.priority_slot_bounds() == {"batch": 1}
        assert ServingConfig().priority_slot_bounds() == {}
        with pytest.raises(ValueError):
            ServingConfig(priority_max_slots="batch:zero")
        with pytest.raises(ValueError):
            ServingConfig(priority_max_slots="urgent:1")

    def test_tiered_requires_paged_pool(self):
        assert _tiered().tiered()
        assert not ServingConfig(num_slots=2).tiered()

    def test_effective_rank_aging(self):
        sched = Scheduler(ServingConfig(num_slots=1,
                                        priority_aging_s=1.0))
        now = 100.0
        assert sched._effective_rank("high", now, now) == 0.0
        assert sched._effective_rank("normal", now, now) == 1.0
        assert sched._effective_rank("batch", now, now) == 2.0
        # 3.5 s waited at 1 s/class: batch outranks fresh high
        aged = sched._effective_rank("batch", now - 3.5, now)
        assert aged == -1.0
        assert aged < sched._effective_rank("high", now, now)
        # aging disabled: rank never improves
        frozen = Scheduler(ServingConfig(num_slots=1,
                                         priority_aging_s=0.0))
        assert frozen._effective_rank("batch", now - 1e6, now) == 2.0

    def test_queue_depths_by_class(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, ServingConfig(
            num_slots=1, prefill_chunk=4, prefill_budget=6))
        assert eng.queue_depths() == {"high": 0, "normal": 0, "batch": 0}
        p1, p2, p3 = _prompts([5, 5, 5], cfg.vocab_size, seed=2)
        eng.submit(p1, max_new_tokens=2, temperature=0.0,
                   priority="high")
        eng.submit(p2, max_new_tokens=2, temperature=0.0,
                   priority="batch")
        eng.submit(p3, max_new_tokens=2, temperature=0.0,
                   priority="batch")
        assert eng.queue_depths() == {"high": 1, "normal": 0,
                                      "batch": 2}
        eng.run()
        assert eng.queue_depths() == {"high": 0, "normal": 0,
                                      "batch": 0}


# ---------------------------------------------------------------------------
# Priority scheduling order (functional, contiguous engine)
# ---------------------------------------------------------------------------


class TestPriorityScheduling:
    def test_high_jumps_queued_batch(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, ServingConfig(
            num_slots=1, prefill_chunk=4, prefill_budget=6))
        pa, pb, pc = _prompts([5, 5, 5], cfg.vocab_size, seed=5)
        rid_a = eng.submit(pa, max_new_tokens=3, temperature=0.0,
                           priority="batch")
        rid_b = eng.submit(pb, max_new_tokens=3, temperature=0.0,
                           priority="batch")
        rid_c = eng.submit(pc, max_new_tokens=3, temperature=0.0,
                           priority="high")
        outs = eng.run()
        # the high request admits first; batch peers keep FCFS order
        assert [o.request_id for o in outs] == [rid_c, rid_a, rid_b]
        for rid, p in ((rid_a, pa), (rid_b, pb), (rid_c, pc)):
            out = next(o for o in outs if o.request_id == rid)
            assert out.tokens == _ref_greedy(params, cfg, p, 3)

    def test_class_slot_bound_caps_batch(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, ServingConfig(
            num_slots=2, prefill_chunk=4, prefill_budget=6,
            priority_max_slots="batch:1"))
        pa, pb, pc = _prompts([5, 5, 5], cfg.vocab_size, seed=6)
        rid_b2 = None
        eng.submit(pa, max_new_tokens=6, temperature=0.0,
                   priority="batch")
        rid_b2 = eng.submit(pb, max_new_tokens=2, temperature=0.0,
                            priority="batch")
        eng.submit(pc, max_new_tokens=2, temperature=0.0,
                   priority="high")
        outs = eng.run()
        # two slots, but batch is capped at one: the short second batch
        # request still finishes LAST, held out while high rides along
        assert outs[-1].request_id == rid_b2

    def test_aging_beats_fresh_high(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, ServingConfig(
            num_slots=1, prefill_chunk=4, prefill_budget=6,
            priority_aging_s=0.05))
        pa, pc = _prompts([5, 5], cfg.vocab_size, seed=8)
        rid_a = eng.submit(pa, max_new_tokens=2, temperature=0.0,
                           priority="batch")
        time.sleep(0.25)  # rank 2 - int(0.25/0.05) < 0 = fresh high
        eng.submit(pc, max_new_tokens=2, temperature=0.0,
                   priority="high")
        outs = eng.run()
        assert outs[0].request_id == rid_a

    def test_without_aging_high_still_wins(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, ServingConfig(
            num_slots=1, prefill_chunk=4, prefill_budget=6,
            priority_aging_s=0.0))
        pa, pc = _prompts([5, 5], cfg.vocab_size, seed=9)
        eng.submit(pa, max_new_tokens=2, temperature=0.0,
                   priority="batch")
        time.sleep(0.25)
        rid_c = eng.submit(pc, max_new_tokens=2, temperature=0.0,
                           priority="high")
        outs = eng.run()
        assert outs[0].request_id == rid_c


# ---------------------------------------------------------------------------
# Demote -> promote round trip (bit-exact, no recompute on revisit)
# ---------------------------------------------------------------------------


def _overflow_until(eng, cfg, stat, floor=1, base=100, limit=30):
    """Push distinct prompts through until ``tier_stats()[stat]``
    reaches ``floor`` (drives radix eviction -> demotion traffic)."""
    k = 0
    while eng.tier_stats()[stat] < floor:
        p = [(base + k) % cfg.vocab_size] + _prompts(
            [16], cfg.vocab_size, seed=base + k)[0]
        eng.generate([p], max_new_tokens=2, temperature=0.0)
        k += 1
        assert k < limit, f"no {stat} after {limit} filler prompts"


class TestTierRoundTrip:
    def test_demote_promote_bit_exact(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _tiered())
        A = [1] + _prompts([16], cfg.vocab_size, seed=7)[0]  # 2 full pages
        ref_a = _ref_greedy(params, cfg, A, 3)
        out = eng.generate([A], max_new_tokens=3, temperature=0.0)[0]
        assert out.tokens == ref_a
        _overflow_until(eng, cfg, "demotions")
        ts0 = eng.tier_stats()
        # revisit: A's pages are host-resident now; the admission
        # promotes them back by copy instead of recomputing prefill
        out2 = eng.generate([A], max_new_tokens=3, temperature=0.0)[0]
        ts1 = eng.tier_stats()
        assert out2.tokens == ref_a
        assert ts1["promotions"] - ts0["promotions"] >= 1
        assert ts1["hits_total"] - ts0["hits_total"] >= 1
        assert ts1["fallbacks"] == 0 and ts1["corrupt_total"] == 0
        assert eng.page_stats()["tier_hits_total"] >= 1


# ---------------------------------------------------------------------------
# Mid-decode preemption + bit-exact resume
# ---------------------------------------------------------------------------


def _run_preempt_scenario(eng, batch_p, high_p, batch_kw, high_kw):
    """Admit a batch request, let it decode a bit, then submit a high
    request that cannot fit -> the scheduler preempts the batch slot.
    Returns {rid: output} after draining."""
    d0 = eng.stats["decode_tokens"]
    rid_b = eng.submit(batch_p, priority="batch", **batch_kw)
    for _ in range(300):
        eng.step()
        if eng.stats["decode_tokens"] - d0 >= 2:
            break
    assert eng.stats["decode_tokens"] - d0 >= 2
    rid_h = eng.submit(high_p, priority="high", **high_kw)
    outs = {o.request_id: o for o in eng.run()}
    return rid_b, rid_h, outs


class TestPreemptResume:
    # pool of 4 pages: the admitted batch request holds 3, the high
    # request needs 2 -> admission blocks and preemption must fire

    def test_preempt_resume_bit_exact_greedy(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg,
                            _tiered(kv_pool_pages=5))
        batch_p, high_p = _prompts([9, 9], cfg.vocab_size, seed=3)
        rid_b, rid_h, outs = _run_preempt_scenario(
            eng, batch_p, high_p,
            dict(max_new_tokens=8, temperature=0.0),
            dict(max_new_tokens=23, temperature=0.0))
        assert outs[rid_h].tokens == _ref_greedy(params, cfg, high_p, 23)
        assert outs[rid_b].tokens == _ref_greedy(params, cfg, batch_p, 8)
        ts = eng.tier_stats()
        assert ts["preemptions"] >= 1 and ts["resumes"] >= 1
        assert ts["fallbacks"] == 0
        assert eng.compile_stats()["decode"] == 1

    def test_preempt_resume_bit_exact_sampled(self):
        # sampled resume leans on the per-request fold_in key chain:
        # token t's key is a pure function of (seed, t), so the swapped
        # request continues the exact stream it would have produced
        cfg, params = _setup("control")
        sv = _tiered(kv_pool_pages=5)
        batch_p, high_p = _prompts([9, 9], cfg.vocab_size, seed=29)
        ref = ServingEngine(params, cfg, sv).generate(
            [batch_p], max_new_tokens=8, temperature=0.9)[0]
        eng = ServingEngine(params, cfg, sv)
        rid_b, _, outs = _run_preempt_scenario(
            eng, batch_p, high_p,
            dict(max_new_tokens=8, temperature=0.9),
            dict(max_new_tokens=23, temperature=0.0))
        assert outs[rid_b].tokens == ref.tokens
        assert eng.stats["preemptions"] >= 1
        assert eng.stats["resumes"] >= 1

    def test_churn_cycle_zero_recompiles(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg,
                            _tiered(kv_pool_pages=5))

        def cycle(base):
            b = [base % cfg.vocab_size] + _prompts(
                [8], cfg.vocab_size, seed=base)[0]
            h = [(base + 1) % cfg.vocab_size] + _prompts(
                [8], cfg.vocab_size, seed=base + 1)[0]
            _run_preempt_scenario(
                eng, b, h,
                dict(max_new_tokens=8, temperature=0.0),
                dict(max_new_tokens=23, temperature=0.0))
            # revisit under pressure: demote/promote churn rides along
            eng.generate([b], max_new_tokens=2, temperature=0.0)

        cycle(11)  # warm: admit/demote/promote/preempt/resume all jit
        p0 = eng.stats["preemptions"]
        with RecompileSentinel(budget=0, name="tier-churn"):
            cycle(17)
        assert eng.stats["preemptions"] > p0
        assert eng.compile_stats()["decode"] == 1


# ---------------------------------------------------------------------------
# Fault drills: every tier failure degrades to a counted recompute
# ---------------------------------------------------------------------------


def _arm_range(name, start, width=300):
    faults.arm(",".join(
        f"{name}@{i}" for i in range(start, start + width)))


class TestTierFaultDrills:
    def test_demote_failure_counts_fallback(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _tiered())
        A = [1] + _prompts([16], cfg.vocab_size, seed=7)[0]
        ref = _ref_greedy(params, cfg, A, 3)
        assert eng.generate(
            [A], max_new_tokens=3, temperature=0.0)[0].tokens == ref
        _arm_range("page_demote_fail", eng.stats["iterations"])
        _overflow_until(eng, cfg, "fallbacks")
        ts = eng.tier_stats()
        assert ts["fallbacks"] >= 1
        assert ts["demotions"] == 0 and ts["entries"] == 0
        faults.reset()
        # graceful: the lost pages simply recompute on revisit
        out = eng.generate([A], max_new_tokens=3, temperature=0.0)[0]
        assert out.tokens == ref

    def test_promote_hang_falls_back_to_recompute(self, monkeypatch):
        monkeypatch.setenv("DTX_TIER_HANG_S", "0.02")
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _tiered())
        A = [1] + _prompts([16], cfg.vocab_size, seed=7)[0]
        ref = _ref_greedy(params, cfg, A, 3)
        eng.generate([A], max_new_tokens=3, temperature=0.0)
        _overflow_until(eng, cfg, "demotions")
        _arm_range("page_promote_hang", eng.stats["iterations"])
        out = eng.generate([A], max_new_tokens=3, temperature=0.0)[0]
        ts = eng.tier_stats()
        assert out.tokens == ref  # recompute fallback, bit-exact
        assert ts["fallbacks"] >= 1 and ts["promotions"] == 0

    def test_swap_corruption_restarts_bit_exact(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg,
                            _tiered(kv_pool_pages=5))
        batch_p, high_p = _prompts([9, 9], cfg.vocab_size, seed=3)
        d0 = eng.stats["decode_tokens"]
        rid_b = eng.submit(batch_p, max_new_tokens=8, temperature=0.0,
                           priority="batch")
        for _ in range(300):
            eng.step()
            if eng.stats["decode_tokens"] - d0 >= 2:
                break
        rid_h = eng.submit(high_p, max_new_tokens=23, temperature=0.0,
                           priority="high")
        _arm_range("page_swap_corrupt", eng.stats["iterations"])
        outs = {o.request_id: o for o in eng.run()}
        ts = eng.tier_stats()
        # the corrupted stash is detected, dropped, and the request
        # RESTARTS from its prompt instead of resuming garbage KV
        assert ts["corrupt_total"] >= 1 and ts["fallbacks"] >= 1
        assert ts["preemptions"] >= 1 and ts["resumes"] == 0
        assert outs[rid_b].tokens == _ref_greedy(params, cfg, batch_p, 8)
        assert outs[rid_h].tokens == _ref_greedy(params, cfg, high_p, 23)


# ---------------------------------------------------------------------------
# Chaos: crash while a preempted request is swapped out
# ---------------------------------------------------------------------------


class TestChaosCrash:
    def test_crash_mid_swap_resumes_bit_identical(self):
        cfg, params = _setup("control")
        sv = _tiered(kv_pool_pages=5)
        batch_p, high_p = _prompts([9, 9], cfg.vocab_size, seed=13)
        ref = ServingEngine(params, cfg, sv).generate(
            [batch_p], max_new_tokens=8, temperature=0.0)[0]
        eng = ServingEngine(params, cfg, sv)
        d0 = eng.stats["decode_tokens"]
        rid_b = eng.submit(batch_p, max_new_tokens=8, temperature=0.0,
                           priority="batch")
        for _ in range(300):
            eng.step()
            if eng.stats["decode_tokens"] - d0 >= 2:
                break
        rid_h = eng.submit(high_p, max_new_tokens=23, temperature=0.0,
                           priority="high")
        for _ in range(300):
            eng.step()
            if eng.stats["preemptions"] >= 1:
                break
        assert eng.stats["preemptions"] >= 1
        faults.arm(f"serve_raise@{eng.stats['iterations'] + 1}")
        with pytest.raises(Exception):
            while eng.has_work():
                eng.step()
        lost = eng.reset_after_crash()
        assert rid_h in lost  # active at crash time -> lost
        ts = eng.tier_stats()
        # the preempted request's stash SURVIVES the crash (it is
        # decode state, not cache); every cached prefix is dropped as
        # untrusted
        assert ts["stashes"] == 1 and ts["entries"] == 0
        outs = {o.request_id: o for o in eng.run()}
        assert outs[rid_b].tokens == ref.tokens
        assert eng.stats["resumes"] >= 1


# ---------------------------------------------------------------------------
# Retry-After from the observed drain rate
# ---------------------------------------------------------------------------


class TestRetryAfter:
    def test_drain_estimate_needs_observations(self):
        pool = PagePool(page_size=4, pages_per_slot=4, num_slots=2,
                        total_pages=9, prefix_cache=True)
        assert pool.estimated_drain_s(2) is None  # no drain observed
        pool.plan_admission(0, list(range(6)), 3)
        pool.release(0, list(range(6)), cacheable=False)
        est = pool.estimated_drain_s(2)
        assert est is not None and est > 0

    def test_shed_carries_retry_after(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _tiered())
        p1, p2, p3 = _prompts([9, 9, 9], cfg.vocab_size, seed=21)
        # successful traffic first: decode past a page boundary so each
        # release returns a decode-only page to the free list — that is
        # what feeds the drain log the Retry-After estimate reads
        eng.generate([p1, p2], max_new_tokens=8, temperature=0.0)
        faults.arm(f"page_exhaust@{eng.stats['iterations']}")
        rid = eng.submit(p3, max_new_tokens=2, temperature=0.0)
        outs = {o.request_id: o for o in eng.run()}
        out = outs[rid]
        assert out.finish_reason == "page_exhausted"
        assert out.retry_after is not None and out.retry_after > 0
        assert eng.stats["page_shed"] >= 1


# ---------------------------------------------------------------------------
# Per-class observability: histograms + SLO objectives
# ---------------------------------------------------------------------------


class TestPriorityObservability:
    def test_per_class_latency_and_slo(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, ServingConfig(
            num_slots=2, prefill_chunk=4, prefill_budget=6))
        p1, p2 = _prompts([5, 5], cfg.vocab_size, seed=17)
        eng.submit(p1, max_new_tokens=2, temperature=0.0,
                   priority="high")
        eng.submit(p2, max_new_tokens=2, temperature=0.0,
                   priority="batch")
        eng.run()
        hist = eng.registry.histogram("serving_class_ttft_seconds",
                                      labelnames=("priority",))
        assert hist.snapshot(priority="high")["count"] == 1
        assert hist.snapshot(priority="batch")["count"] == 1
        assert hist.snapshot(priority="normal")["count"] == 0
        latency, availability = default_serving_objectives()
        mon = SLOMonitor(eng.registry, latency=latency,
                         availability=availability)
        out = mon.evaluate()
        assert out["ttft_high"]["count"] == 1.0
        assert out["ttft_batch"]["count"] == 1.0
        # a class with no traffic never alarms
        assert out["ttft_normal"]["error_ratio"] is None
        assert out["ttft_normal"]["burn_rate"] is None


# ---------------------------------------------------------------------------
# Acceptance: 10x working set sustained through the host tier
# ---------------------------------------------------------------------------


class TestWorkingSetTiering:
    def test_10x_working_set_sustains_hits(self):
        cfg, params = _setup("control")
        eng = ServingEngine(params, cfg, _tiered())
        pool_pages = eng.page_stats()["total"]
        # 10x the device pool in 2-full-page prefixes
        n_prefix = 10 * pool_pages // 2
        rng = np.random.default_rng(33)
        prompts = []
        for j in range(n_prefix):
            prefix = [j % cfg.vocab_size] + rng.integers(
                0, cfg.vocab_size, 15).tolist()
            prompts.append(prefix + [int(rng.integers(0, cfg.vocab_size))])
        outs = eng.generate(prompts, max_new_tokens=2, temperature=0.0)
        assert all(o.finish_reason != "page_exhausted" for o in outs)
        ts0 = eng.tier_stats()
        outs = eng.generate(prompts, max_new_tokens=2, temperature=0.0)
        assert all(o.finish_reason != "page_exhausted" for o in outs)
        ts1 = eng.tier_stats()
        hits = ts1["hits_total"] - ts0["hits_total"]
        misses = ts1["misses_total"] - ts0["misses_total"]
        assert hits + misses > 0
        assert hits / (hits + misses) >= 0.8
        # reuse came through the tier, not the 6-page device pool
        assert hits >= n_prefix
        assert ts1["fallbacks"] == 0 and ts1["corrupt_total"] == 0
        assert eng.stats["page_shed"] == 0
        assert eng.stats["engine_restarts"] == 0
